"""Rego lexer."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

KEYWORDS = {
    "package", "import", "default", "not", "some", "as", "with", "else",
    "true", "false", "null", "in", "every", "if", "contains",
}

TWO_CHAR = {":=", "==", "!=", "<=", ">="}
ONE_CHAR = set("=<>+-*/%&|;,.:[](){}")


class LexError(SyntaxError):
    pass


@dataclass(frozen=True)
class Token:
    kind: str  # ident | keyword | number | string | op | newline | eof
    value: str
    line: int
    col: int

    def __repr__(self):
        return f"Token({self.kind},{self.value!r}@{self.line})"


def tokenize(src: str) -> list[Token]:
    toks: list[Token] = []
    i, line, col = 0, 1, 1
    n = len(src)

    def push(kind, value, ln=None, cl=None):
        toks.append(Token(kind, value, ln or line, cl or col))

    while i < n:
        c = src[i]
        if c == "\n":
            push("newline", "\n")
            i += 1
            line += 1
            col = 1
            continue
        if c in " \t\r":
            i += 1
            col += 1
            continue
        if c == "#":
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c == "`":  # raw string
            j = src.find("`", i + 1)
            if j < 0:
                raise LexError(f"unterminated raw string at line {line}")
            push("string", src[i + 1 : j])
            col += j - i + 1
            nl = src.count("\n", i, j)
            if nl:
                line += nl
            i = j + 1
            continue
        if c == '"':
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\\":
                    if j + 1 >= n:
                        raise LexError(f"unterminated escape at line {line}")
                    esc = src[j + 1]
                    if esc == "u":
                        hexs = src[j + 2 : j + 6]
                        if len(hexs) < 4 or any(
                            c not in "0123456789abcdefABCDEF" for c in hexs
                        ):
                            raise LexError(f"bad \\u escape at line {line}")
                        buf.append(chr(int(hexs, 16)))
                        j += 6
                        continue
                    buf.append(
                        {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\",
                         "/": "/", "b": "\b", "f": "\f"}.get(esc, "\\" + esc)
                    )
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise LexError(f"unterminated string at line {line}")
            push("string", "".join(buf))
            col += j - i + 1
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            while j < n and (src[j].isdigit() or src[j] in ".eE+-"):
                # stop '+-' unless exponent
                if src[j] in "+-" and src[j - 1] not in "eE":
                    break
                j += 1
            push("number", src[i:j])
            col += j - i
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            push("keyword" if word in KEYWORDS else "ident", word)
            col += j - i
            i = j
            continue
        if src[i : i + 2] in TWO_CHAR:
            push("op", src[i : i + 2])
            i += 2
            col += 2
            continue
        if c in ONE_CHAR:
            push("op", c)
            i += 1
            col += 1
            continue
        raise LexError(f"unexpected character {c!r} at line {line}:{col}")
    push("eof", "")
    return toks
