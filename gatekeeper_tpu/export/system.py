"""Violation export plane.

Reference: pkg/export — ``System`` maps Connection CRs to pluggable drivers;
the audit publishes audit_started / violation / audit_ended messages
(audit/manager.go:267-295,931-936).  Drivers here: **disk** (rotating
audit-run files, reference disk/disk.go), **stdout**, and **dapr**
(pub-sub publish through the localhost sidecar HTTP API, reference
export/dapr/dapr.go:93).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class ExportError(Exception):
    pass


class DiskDriver:
    """Rotating per-audit-run violation files (reference: export/disk)."""

    def __init__(self, path: str, max_audit_results: int = 3):
        self.base = path
        self.max_audit_results = max_audit_results
        self._current: Optional[object] = None
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)

    def publish(self, msg: dict) -> None:
        with self._lock:
            if msg.get("event") == "audit_started":
                self._rotate(msg.get("auditID", ""))
            if self._current is not None:
                self._current.write(json.dumps(msg) + "\n")
                self._current.flush()
            if msg.get("event") == "audit_ended" and self._current:
                self._current.close()
                self._current = None

    def _rotate(self, audit_id: str) -> None:
        if self._current is not None:
            self._current.close()
        safe = audit_id.replace(":", "_").replace("/", "_") or str(
            int(time.time()))
        self._current = open(
            os.path.join(self.base, f"audit_{safe}.jsonl"), "w")
        self._cleanup()

    def _cleanup(self) -> None:
        """Keep only the newest N runs (reference: disk/cleanup.go)."""
        runs = sorted(
            (f for f in os.listdir(self.base) if f.startswith("audit_")),
            key=lambda f: os.path.getmtime(os.path.join(self.base, f)),
        )
        for f in runs[: max(0, len(runs) - self.max_audit_results)]:
            os.unlink(os.path.join(self.base, f))


class StdoutDriver:
    def publish(self, msg: dict) -> None:
        print("export:", json.dumps(msg), flush=True)


class DaprDriver:
    """dapr pub-sub export (reference: export/dapr/dapr.go): publishes
    each message to the local sidecar's HTTP API,
    POST http://127.0.0.1:<port>/v1.0/publish/<component>/<topic>.  The
    sidecar port follows the DAPR_HTTP_PORT convention."""

    def __init__(self, component: str = "pubsub",
                 topic: str = "audit-channel",
                 port: Optional[int] = None,
                 timeout_s: float = 5.0):
        self.component = component
        self.topic = topic
        self.port = port if port is not None else int(
            os.environ.get("DAPR_HTTP_PORT", "3500"))
        self.timeout_s = timeout_s

    def publish(self, msg: dict) -> None:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/v1.0/publish/"
            f"{self.component}/{self.topic}",
            data=json.dumps(msg).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                if r.status >= 300:
                    raise ExportError(
                        f"dapr sidecar returned {r.status}")
        except ExportError:
            raise
        except Exception as e:
            raise ExportError(f"dapr publish failed: {e}") from e


DRIVERS = {"disk": DiskDriver, "stdout": StdoutDriver, "dapr": DaprDriver}


class ExportSystem:
    """Connection registry + publish fan-in (reference: export/system.go)."""

    def __init__(self):
        self._connections: dict[str, object] = {}
        self._lock = threading.Lock()

    def upsert_connection(self, name: str, driver: str, config: dict) -> None:
        cls = DRIVERS.get(driver)
        if cls is None:
            raise ExportError(f"unknown export driver {driver!r}")
        with self._lock:
            if driver == "disk":
                self._connections[name] = cls(
                    config.get("path", "/tmp/gatekeeper-exports"),
                    int(config.get("maxAuditResults", 3)),
                )
            elif driver == "dapr":
                self._connections[name] = cls(
                    component=config.get("component", "pubsub"),
                    topic=config.get("topic", "audit-channel"),
                    port=(int(config["port"]) if "port" in config
                          else None),
                )
            else:
                self._connections[name] = cls()

    def upsert_connection_cr(self, obj: dict) -> None:
        """Connection CR (reference: apis/connection + export controller)."""
        spec = obj.get("spec") or {}
        name = (obj.get("metadata") or {}).get("name", "")
        self.upsert_connection(name, spec.get("driver", ""),
                               spec.get("config") or {})

    def remove_connection(self, name: str) -> None:
        with self._lock:
            self._connections.pop(name, None)

    def publish(self, msg: dict) -> list:
        """Returns per-connection errors (fed back into connection status in
        the reference, audit/manager.go:1317-1340)."""
        errors = []
        with self._lock:
            conns = list(self._connections.items())
        for name, driver in conns:
            try:
                driver.publish(msg)
            except Exception as e:
                errors.append((name, str(e)))
        return errors

    # audit-facing helpers (message shapes per audit/manager.go:267-295)
    def publish_audit_started(self, audit_id: str):
        return self.publish({"event": "audit_started", "auditID": audit_id})

    def publish_violation(self, audit_id: str, violation) -> list:
        return self.publish({
            "event": "violation",
            "auditID": audit_id,
            "constraint": str(violation.constraint.key()),
            "enforcementAction": violation.enforcement_action,
            "group": violation.group,
            "version": violation.version,
            "kind": violation.kind,
            "namespace": violation.namespace,
            "name": violation.name,
            "message": violation.message,
        })

    def publish_audit_ended(self, audit_id: str):
        return self.publish({"event": "audit_ended", "auditID": audit_id})
