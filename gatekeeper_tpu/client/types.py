"""Response types of the constraint-framework client.

Reference surface (SURVEY.md §2.8): ``types.Responses{ByTarget, StatsEntries}``,
``types.Result{Target, Msg, Constraint, Metadata, EnforcementAction,
ScopedEnforcementActions}``, ``instrumentation.StatsEntry``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Result:
    target: str
    msg: str
    constraint: dict  # raw constraint object
    metadata: dict = field(default_factory=dict)  # {"details": ...}
    enforcement_action: str = "deny"
    scoped_enforcement_actions: list = field(default_factory=list)

    @property
    def details(self) -> Any:
        return self.metadata.get("details")


@dataclass
class Stat:
    name: str
    value: Any
    source: dict = field(default_factory=dict)  # {type, value}


@dataclass
class StatsEntry:
    scope: str
    stats_for: str
    stats: list = field(default_factory=list)
    labels: list = field(default_factory=list)


@dataclass
class Response:
    target: str
    results: list = field(default_factory=list)  # list[Result]
    trace: Optional[str] = None


@dataclass
class Responses:
    by_target: dict = field(default_factory=dict)  # target -> Response
    stats_entries: list = field(default_factory=list)

    def results(self) -> list:
        out = []
        for target in sorted(self.by_target):
            out.extend(self.by_target[target].results)
        return out

    def trace_dump(self) -> str:
        chunks = []
        for target in sorted(self.by_target):
            resp = self.by_target[target]
            if resp.trace:
                chunks.append(f"target: {target}\n{resp.trace}")
        return "\n\n".join(chunks)


@dataclass
class QueryResponse:
    """What a Driver.query returns (reference: drivers.QueryResponse,
    mirrored at pkg/drivers/k8scel/driver.go:250)."""

    results: list = field(default_factory=list)
    stats_entries: list = field(default_factory=list)
    trace: Optional[str] = None
