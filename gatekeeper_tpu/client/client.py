"""Constraint-framework client: the L1 multiplexer.

Rebuild of the external module ``frameworks/constraint`` client surface the
reference consumes (SURVEY.md §2.8): templates/constraints are held per
target, each template is compiled by the highest-priority driver that
understands its source (driver priority = registration order, main.go:460-498),
``review`` routes through the target handler, prefilters constraints with the
match predicate, fans out per-engine ``query`` calls and merges responses.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

from gatekeeper_tpu.apis.constraints import Constraint, ConstraintError
from gatekeeper_tpu.apis.templates import ConstraintTemplate, TemplateError
from gatekeeper_tpu.client.types import QueryResponse, Response, Responses
from gatekeeper_tpu.drivers.base import ReviewCfg
from gatekeeper_tpu.match.match import label_selector_matches
from gatekeeper_tpu.target.target import K8sValidationTarget, WipeData


class ClientError(Exception):
    pass


class Client:
    def __init__(
        self,
        target: Optional[K8sValidationTarget] = None,
        drivers: Sequence[Any] = (),
        enforcement_points: Sequence[str] = (),
    ):
        if not drivers:
            raise ClientError("at least one driver is required")
        self.target = target or K8sValidationTarget()
        self.drivers = list(drivers)
        self.enforcement_points = list(enforcement_points)
        self._templates: dict[str, ConstraintTemplate] = {}  # by kind
        self._template_driver: dict[str, Any] = {}  # kind -> driver
        self._constraints: dict[str, dict[str, Constraint]] = {}  # kind -> name -> c

    # --- templates ----------------------------------------------------
    def create_crd(self, template_obj: dict) -> dict:
        """Validate a template and synthesize its constraint CRD without
        installing (reference: Client.CreateCRD, used for webhook dry-run
        validation at policy.go:430)."""
        template = self._parse_template(template_obj)
        return template.constraint_crd()

    def add_template(self, template_obj: dict) -> dict:
        """Compile + install a template; returns the generated constraint CRD.

        Reference: Client.AddTemplate (controller call site
        constrainttemplate_controller.go:479).
        """
        template = self._parse_template(template_obj)
        driver = self._driver_for(template)
        driver.add_template(template)
        old = self._template_driver.get(template.kind)
        if old is not None and old is not driver:
            old.remove_template(template.kind)
        self._templates[template.kind] = template
        self._template_driver[template.kind] = driver
        self._constraints.setdefault(template.kind, {})
        return template.constraint_crd()

    def remove_template(self, template_obj_or_kind: Any) -> None:
        kind = (
            template_obj_or_kind
            if isinstance(template_obj_or_kind, str)
            else self._parse_template(template_obj_or_kind).kind
        )
        driver = self._template_driver.pop(kind, None)
        if driver is not None:
            driver.remove_template(kind)
        self._templates.pop(kind, None)
        self._constraints.pop(kind, None)

    def get_template(self, kind: str) -> Optional[ConstraintTemplate]:
        return self._templates.get(kind)

    def templates(self) -> list[ConstraintTemplate]:
        return list(self._templates.values())

    def _parse_template(self, obj: Any) -> ConstraintTemplate:
        if isinstance(obj, ConstraintTemplate):
            return obj
        return ConstraintTemplate.from_unstructured(obj)

    def _driver_for(self, template: ConstraintTemplate) -> Any:
        for driver in self.drivers:
            if driver.has_source_for(template):
                return driver
        raise TemplateError(
            f"template {template.name}: no driver understands its source"
        )

    # --- constraints --------------------------------------------------
    def add_constraint(self, constraint_obj: dict) -> Constraint:
        constraint = Constraint.from_unstructured(constraint_obj)
        if constraint.kind not in self._templates:
            raise ClientError(
                f"no template registered for constraint kind {constraint.kind}"
            )
        self.validate_constraint(constraint_obj)
        self._template_driver[constraint.kind].add_constraint(constraint)
        self._constraints[constraint.kind][constraint.name] = constraint
        return constraint

    def remove_constraint(self, constraint_obj: dict) -> None:
        try:
            constraint = Constraint.from_unstructured(constraint_obj)
        except ConstraintError:
            return
        by_name = self._constraints.get(constraint.kind)
        if by_name and constraint.name in by_name:
            self._template_driver[constraint.kind].remove_constraint(constraint)
            del by_name[constraint.name]

    def get_constraint(self, kind: str, name: str) -> Optional[Constraint]:
        return self._constraints.get(kind, {}).get(name)

    def constraints(self) -> list[Constraint]:
        out = []
        for by_name in self._constraints.values():
            out.extend(by_name.values())
        return out

    def validate_constraint(self, constraint_obj: dict) -> None:
        """Reference: Client.ValidateConstraint + target.ValidateConstraint
        (target.go:185-221) — label selector sanity."""
        constraint = Constraint.from_unstructured(constraint_obj)
        constraint.validate_actions()
        for sel_key in ("labelSelector", "namespaceSelector"):
            sel = constraint.match.get(sel_key)
            if sel is not None:
                # surface bad operators early
                label_selector_matches(sel, {})

    # --- data plane ---------------------------------------------------
    def add_data(self, obj: Any) -> None:
        handled, path, data = self.target.process_data(obj)
        if not handled or path is None:
            if isinstance(obj, WipeData) or obj is WipeData:
                for driver in self.drivers:
                    if hasattr(driver, "wipe_data"):
                        driver.wipe_data()
                self.target.cache.wipe()
            return
        if isinstance(obj, dict):
            self.target.cache.add(obj)
        for driver in self.drivers:
            driver.add_data(self.target.name, path, data)

    def remove_data(self, obj: Any) -> None:
        handled, path, _ = self.target.process_data(obj)
        if not handled or path is None:
            return
        if isinstance(obj, dict):
            self.target.cache.remove(obj)
        for driver in self.drivers:
            driver.remove_data(self.target.name, path)

    # --- review (the hot path) ----------------------------------------
    def review(
        self,
        review_obj: Any,
        enforcement_point: str = "",
        tracing: bool = False,
        stats: bool = False,
    ) -> Responses:
        """Reference: Client.Review (webhook policy.go:664, audit
        manager.go:720, gator test.go:118)."""
        review = self.target.handle_review(review_obj)
        if review is None:
            raise ClientError(f"unrecognized review type {type(review_obj)}")
        cfg = ReviewCfg(
            enforcement_point=enforcement_point, tracing=tracing, stats=stats
        )
        responses = Responses()
        response = Response(target=self.target.name)

        # group matching constraints per driver, preserving constraint order
        by_driver: dict[int, tuple[Any, list[Constraint]]] = {}
        for kind in sorted(self._constraints):
            by_name = self._constraints[kind]
            driver = self._template_driver[kind]
            for name in sorted(by_name):
                constraint = by_name[name]
                actions = constraint.actions_for(enforcement_point) if (
                    enforcement_point
                ) else [constraint.enforcement_action]
                if not actions:
                    continue  # scoped constraint inactive at this EP
                if not self.target.to_matcher(constraint.match).match(review):
                    continue
                entry = by_driver.setdefault(id(driver), (driver, []))
                entry[1].append(constraint)

        for driver, constraints in by_driver.values():
            qr: QueryResponse = driver.query(
                self.target.name, constraints, review, cfg
            )
            for result in qr.results:
                constraint = self._constraint_for_result(result)
                if constraint is not None:
                    self._resolve_actions(result, constraint, enforcement_point)
                response.results.append(result)
            responses.stats_entries.extend(qr.stats_entries)
            if qr.trace:
                response.trace = (
                    (response.trace + "\n" + qr.trace) if response.trace else qr.trace
                )
        responses.by_target[self.target.name] = response
        return responses

    def review_batch(
        self,
        review_objs: Sequence[Any],
        enforcement_point: str = "",
        tracing: bool = False,
        stats: bool = False,
    ) -> list:
        """Batched reviews (the webhook microbatch lane / audit-from-cache).

        Returns one entry per input: a ``Responses`` on success or an
        ``Exception`` for that input alone (a bad request must not poison the
        rest of a coalesced webhook batch).  Constraint kinds owned by a
        batch-capable driver evaluate in one ``query_batch`` pass; kinds
        owned by other drivers fan out per-review exactly like ``review``.
        """
        batch_driver = next(
            (d for d in self.drivers if hasattr(d, "query_batch")), None
        )
        if batch_driver is None:
            out = []
            for obj in review_objs:
                try:
                    out.append(self.review(obj, enforcement_point, tracing,
                                           stats))
                except Exception as e:
                    out.append(e)
            return out

        entries: list = []  # per input: GkReview or Exception
        for obj in review_objs:
            try:
                r = self.target.handle_review(obj)
                if r is None:
                    raise ClientError(
                        f"unrecognized review type {type(obj)}"
                    )
                entries.append(r)
            except Exception as e:
                entries.append(e)

        active = [
            c for c in sorted(self.constraints(), key=Constraint.key)
            if (c.actions_for(enforcement_point) if enforcement_point
                else [c.enforcement_action])
        ]
        batch_cons = [
            c for c in active
            if self._template_driver.get(c.kind) is batch_driver
        ]
        other_cons = [
            c for c in active
            if self._template_driver.get(c.kind) is not batch_driver
        ]
        cfg = ReviewCfg(enforcement_point=enforcement_point, tracing=tracing,
                        stats=stats)

        valid_idx = [i for i, e in enumerate(entries)
                     if not isinstance(e, Exception)]
        reviews = [entries[i] for i in valid_idx]
        q_responses = batch_driver.query_batch(
            self.target.name, batch_cons, reviews, cfg
        ) if batch_cons else [QueryResponse() for _ in reviews]

        out: list = [None] * len(entries)
        for slot, (i, qr) in enumerate(zip(valid_idx, q_responses)):
            responses = Responses()
            response = Response(target=self.target.name)
            for result in qr.results:
                constraint = self._constraint_for_result(result)
                if constraint is not None:
                    self._resolve_actions(result, constraint,
                                          enforcement_point)
                response.results.append(result)
            responses.stats_entries.extend(qr.stats_entries)
            if qr.trace:
                response.trace = qr.trace
            # kinds owned by non-batch drivers: per-review query, matching
            # review()'s per-driver fan-out
            review = reviews[slot]
            try:
                for con in other_cons:
                    if not self.target.to_matcher(con.match).match(review):
                        continue
                    driver = self._template_driver[con.kind]
                    oqr = driver.query(self.target.name, [con], review, cfg)
                    for result in oqr.results:
                        self._resolve_actions(result, con, enforcement_point)
                        response.results.append(result)
                    responses.stats_entries.extend(oqr.stats_entries)
                    if oqr.trace:
                        response.trace = (
                            (response.trace + "\n" + oqr.trace)
                            if response.trace else oqr.trace
                        )
            except Exception as e:
                out[i] = e
                continue
            responses.by_target[self.target.name] = response
            out[i] = responses
        for i, e in enumerate(entries):
            if isinstance(e, Exception):
                out[i] = e
        return out

    def _constraint_for_result(self, result) -> Optional[Constraint]:
        c = result.constraint or {}
        kind = c.get("kind", "")
        name = (c.get("metadata") or {}).get("name", "")
        return self.get_constraint(kind, name)

    @staticmethod
    def _resolve_actions(result, constraint: Constraint, ep: str) -> None:
        result.enforcement_action = constraint.enforcement_action
        if constraint.enforcement_action == "scoped":
            result.scoped_enforcement_actions = (
                constraint.actions_for(ep) if ep else
                [e.get("action", "deny") for e in constraint.scoped_actions]
            )

    # --- introspection -------------------------------------------------
    def dump(self) -> dict:
        return {d.name(): d.dump() for d in self.drivers}

    def get_description_for_stat(self, source: dict, stat_name: str) -> str:
        for d in self.drivers:
            if source.get("value") == d.name():
                return d.get_description_for_stat(stat_name)
        return "unknown stat"
