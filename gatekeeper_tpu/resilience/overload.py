"""Overload protection for the serving path: adaptive concurrency,
cost-aware load shedding, brownout ladder, graceful-drain state machine.

The resilience layer (policy.py) protects the server from *dependency*
failures; this module protects it from *its own* overload.  The
reference delegates this tier to kube-apiserver's API Priority and
Fairness (the webhook rides the apiserver's own flow control); we are
our own server, so we carry our own limiter, in the gradient/AIMD
adaptive-concurrency shape production inference gateways use:

- :class:`AdaptiveLimiter` — AIMD on observed review latency vs a
  seeded-deterministic baseline EWMA.  Latency above
  ``threshold × baseline`` over an update window multiplicatively
  decreases the in-flight limit; healthy windows additively increase
  it.  Deterministic for a given (seed, sample sequence), so tests
  replay the exact limit trajectory.
- :class:`OverloadController` — the admission gate in front of
  ``ValidationHandler``: a bounded **cost-aware queue** (cost = object
  bytes × matched-constraint estimate) holds requests that arrive while
  the limiter is full; a request that cannot queue (bounds exceeded,
  queue-wait timeout) is **shed** by raising :class:`Shed`, which the
  webhook maps onto the request's ``failurePolicy`` exactly like a
  deadline miss (Ignore = allow + warning annotation, Fail = 429 with
  Retry-After).
- **Brownout ladder** — before any validation request is shed, the
  controller degrades expensive *optional* work first, driven by queue
  pressure: level 1 serves namespace-label lookups and external-data
  joins stale-from-cache; level 2 additionally makes the audit sweep
  yield the device lane (:func:`yield_device_lane`).  Level 0 is
  bit-identical to no limiter at all (the overload differential test
  pins this).
- :class:`DrainCoordinator` — the graceful-drain state machine
  (``serving → draining → stopped``) wired into ``__main__``: on
  SIGTERM readiness flips 503, the listener stops accepting, in-flight
  handlers and the Batcher queue drain within ``--drain-timeout``, the
  tracer/metrics flush, worker children drain in sequence — zero
  in-flight verdicts lost.

Activation mirrors faults.py: :func:`install` process-global (the CLI),
:func:`activate` contextvar-free scoped helper for tests, and cheap
module-level reads (:func:`current_brownout`) for consumers on other
layers (externaldata, audit).
"""

from __future__ import annotations

import json
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Optional


class Shed(Exception):
    """The admission gate refused this request (queue bounds exceeded,
    queue-wait timeout, or an injected ``webhook.overload`` chaos
    fault).  The webhook resolves it per the request's failurePolicy —
    never by dropping the connection."""

    def __init__(self, reason: str = "overload",
                 retry_after_s: float = 1.0):
        super().__init__(f"request shed under overload ({reason}); "
                         f"retry in {retry_after_s:.1f}s")
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass
class OverloadConfig:
    """Knobs for the limiter + admission queue + brownout ladder."""

    # per-tenant / per-priority QoS (resilience/qos.py): a parsed
    # QoSConfig replaces the single FIFO with priority lanes +
    # weighted-fair (deficit-round-robin) dequeue across tenants,
    # per-tenant inflight caps / queue-cost budgets, and tenant-aware
    # displacement.  None (the compat default, `--qos off`) keeps the
    # PR 5 single-FIFO path bit-identical (differential-tested).
    qos: Optional[object] = None

    # adaptive concurrency (AIMD)
    min_inflight: int = 1
    max_inflight: int = 64
    initial_inflight: int = 8
    ewma_alpha: float = 0.1  # baseline EWMA smoothing
    latency_threshold: float = 2.0  # window avg > threshold*baseline: back off
    decrease_factor: float = 0.7  # multiplicative decrease
    increase_step: float = 1.0  # additive increase per healthy window
    update_window: int = 16  # samples per AIMD decision
    # fraction of *congested* samples fed to the baseline EWMA (seeded
    # RNG): the baseline tracks slow drift without learning queueing
    # delay as the new normal
    congested_sample_p: float = 0.05
    seed: int = 0
    # cost-aware admission queue (cost = object bytes x matched-constraint
    # estimate); both bounds shed when exceeded
    queue_depth: int = 256
    queue_cost: float = 256e6
    queue_timeout_s: float = 1.0  # max wait for a limiter slot
    shed_retry_after_s: float = 1.0
    # brownout ladder thresholds on queue fill fraction
    # (max of depth-fill and cost-fill), with exit hysteresis
    brownout1_enter: float = 0.05
    brownout1_exit: float = 0.0
    brownout2_enter: float = 0.5
    brownout2_exit: float = 0.25


class AdaptiveLimiter:
    """AIMD in-flight limiter against a seeded-deterministic latency
    baseline EWMA.

    The baseline learns from samples observed while the lane was
    *uncongested* (in-flight at release time ≤ half the limit) plus a
    seeded ``congested_sample_p`` trickle of loaded samples, so a
    sustained overload cannot teach the limiter that queueing delay is
    normal.  Every decision is a pure function of (config, seed, sample
    sequence): tests replay the exact limit trajectory."""

    def __init__(self, config: Optional[OverloadConfig] = None,
                 metrics=None):
        self.config = config or OverloadConfig()
        c = self.config
        self.metrics = metrics
        self._limit = float(
            min(c.max_inflight, max(c.min_inflight, c.initial_inflight)))
        self._inflight = 0
        self._baseline: Optional[float] = None
        self._win_sum = 0.0
        self._win_n = 0
        self._rng = random.Random(c.seed)
        self._lock = threading.Lock()
        self._export()

    @property
    def limit(self) -> int:
        with self._lock:
            return int(self._limit)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def baseline_s(self) -> Optional[float]:
        with self._lock:
            return self._baseline

    def try_acquire(self) -> bool:
        with self._lock:
            if self._inflight < int(self._limit):
                self._inflight += 1
                return True
            return False

    def cancel(self) -> None:
        """Give back a slot WITHOUT a latency sample (the QoS
        dispatcher speculatively acquires before picking a ticket; a
        pick that comes back empty must not feed the AIMD window)."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def release(self, latency_s: float) -> None:
        c = self.config
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            # uncongested at release: this sample measured service time,
            # not queueing — feed the baseline
            uncongested = (self._inflight + 1) <= max(
                1, int(self._limit) // 2)
            if self._baseline is None:
                self._baseline = latency_s
            elif uncongested or self._rng.random() < c.congested_sample_p:
                self._baseline += c.ewma_alpha * (
                    latency_s - self._baseline)
            self._win_sum += latency_s
            self._win_n += 1
            if self._win_n >= c.update_window:
                avg = self._win_sum / self._win_n
                self._win_sum, self._win_n = 0.0, 0
                if self._baseline and \
                        avg > c.latency_threshold * self._baseline:
                    self._limit = max(float(c.min_inflight),
                                      self._limit * c.decrease_factor)
                else:
                    self._limit = min(float(c.max_inflight),
                                      self._limit + c.increase_step)
        self._export()

    def _export(self) -> None:
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as M

        self.metrics.set_gauge(M.OVERLOAD_INFLIGHT_LIMIT, self.limit)


def estimate_cost(review_body: dict, cost_hint: int = 0,
                  constraint_count: Optional[Callable[[str], int]] = None
                  ) -> float:
    """Admission cost = object bytes × matched-constraint estimate.

    ``cost_hint`` is the HTTP Content-Length when the server knows it
    (the cheap path); otherwise the request object is sized by one
    compact serialize.  ``constraint_count(kind)`` is the caller's
    cached matched-constraint estimator (ValidationHandler caches per
    kind)."""
    req = review_body.get("request") or {}
    nbytes = int(cost_hint or 0)
    if nbytes <= 0:
        obj = req.get("object")
        if obj is not None:
            try:
                nbytes = len(json.dumps(obj, separators=(",", ":")))
            except (TypeError, ValueError):
                nbytes = 1024
        else:
            nbytes = 64
    n_cons = 1
    if constraint_count is not None:
        kind = ((req.get("kind") or {}).get("kind", "")) or ""
        try:
            n_cons = max(1, int(constraint_count(kind)))
        except Exception:
            n_cons = 1
    return float(max(1, nbytes)) * n_cons


# --- degradation registry (per-objective SLO degradation maps) ------------

# built-in action names: the vocabulary objectives' ``degradation``
# maps draw from.  Consumers poll :func:`degradation_active` — the
# registry holds WHO degraded WHAT and why; the consumers stay dumb.
NS_CACHE_STALE = "ns_cache_stale"
EXTDATA_STALE = "extdata_stale"
SHED_HARDER = "shed_harder"
AUDIT_YIELD_RELEASE = "audit_yield_release"
RESYNC_DEFER = "resync_defer"
DEVICE_RESIDENCY_EVICT = "device_residency_evict"

BUILTIN_ACTIONS = {
    NS_CACHE_STALE:
        "serve namespace-label lookups stale-from-cache",
    EXTDATA_STALE:
        "serve external-data joins stale from resident columns",
    SHED_HARDER:
        "halve the admission queue bounds so overload sheds earlier",
    AUDIT_YIELD_RELEASE:
        "stop yielding the device lane to admissions (audit catches up)",
    RESYNC_DEFER:
        "defer the audit's periodic full resync",
    DEVICE_RESIDENCY_EVICT:
        "demote device-resident snapshot groups back to host columns "
        "(frees HBM; ticks re-pay the H2D wire until release)",
}


class DegradationRegistry:
    """Named, revocable degradation actions the SLO engine activates
    per objective (observability/slo.py degradation maps).

    Where the brownout ladder is one scalar — queue pressure degrades
    EVERYTHING a level at a time — the registry is targeted: a
    breaching ``admission-latency-p99`` activates ``ns_cache_stale``
    without touching the audit lane, and a breaching
    ``audit-snapshot-staleness`` releases the audit's device-lane
    yield without staling the webhook's caches.  Activations are
    reference-held per (action, cluster): several objectives may hold
    the same action; the action releases only when the last holder
    lets go.  Cluster-scoped activations (fleet mode) never leak:
    a consumer asking with ``cluster="b"`` sees only global (``""``)
    and ``"b"``-scoped activations."""

    def __init__(self, metrics=None):
        self.metrics = metrics
        self._lock = threading.Lock()
        self._known = dict(BUILTIN_ACTIONS)
        self._hooks: dict = {}  # name -> (on_activate, on_release)
        # (action, cluster) -> set of holder objective names
        self._active: dict = {}
        self.transitions = 0  # total activate/release edges

    # --- registration ----------------------------------------------------
    def register(self, name: str, description: str = "",
                 on_activate=None, on_release=None) -> None:
        """Declare an action (consumers: overload controller, the
        ProviderCache, the AuditManager).  ``on_activate(cluster)`` /
        ``on_release(cluster)`` fire on the action's rising/falling
        edge; exceptions are swallowed — degradation must never take
        the server down."""
        with self._lock:
            self._known[name] = description or self._known.get(name, "")
            if on_activate is not None or on_release is not None:
                self._hooks[name] = (on_activate, on_release)

    def known(self) -> set:
        with self._lock:
            return set(self._known)

    def describe(self, name: str) -> str:
        with self._lock:
            return self._known.get(name, "")

    def validate(self, actions, where: str = "") -> None:
        """Raise ``ValueError`` naming the first unknown action — the
        boot-time check behind ``--slo-config`` degradation maps."""
        known = self.known()
        for a in actions:
            if a not in known:
                raise ValueError(
                    f"{where or 'degradation map'}: unknown degradation "
                    f"action {a!r} (registered: {sorted(known)})")

    # --- activation ------------------------------------------------------
    def activate(self, name: str, objective: str = "",
                 cluster: str = "") -> bool:
        """Hold ``name`` active on behalf of ``objective`` (scoped to
        ``cluster``; ``""`` = global).  True on the rising edge."""
        with self._lock:
            if name not in self._known:
                raise ValueError(f"unknown degradation action {name!r}")
            holders = self._active.setdefault((name, cluster), set())
            rising = not holders
            holders.add(objective or "")
            if rising:
                self.transitions += 1
        self._export(name, objective, cluster, 1.0)
        if rising:
            self._fire(name, cluster, 0)
        return rising

    def release(self, name: str, objective: str = "",
                cluster: str = "") -> bool:
        """Let go of ``name``; True on the falling edge (last holder
        released)."""
        with self._lock:
            holders = self._active.get((name, cluster))
            if holders is None:
                return False
            holders.discard(objective or "")
            falling = not holders
            if falling:
                del self._active[(name, cluster)]
                self.transitions += 1
        self._export(name, objective, cluster, 0.0)
        if falling:
            self._fire(name, cluster, 1)
        return falling

    def is_active(self, name: str, cluster: str = "") -> bool:
        """Does this action bind a consumer scoped to ``cluster``?
        Global activations bind every scope; cluster-scoped ones bind
        only their own cluster (the fleet isolation pin)."""
        with self._lock:
            if self._active.get((name, "")):
                return True
            return bool(cluster and self._active.get((name, cluster)))

    def active(self) -> list:
        """[{action, cluster, objectives}] snapshot, sorted — the
        ``/debug/overload`` + flight-recorder view."""
        with self._lock:
            return [{"action": n, "cluster": c,
                     "objectives": sorted(hs)}
                    for (n, c), hs in sorted(self._active.items())]

    def active_names(self) -> list:
        """Compact ``action`` / ``action@cluster`` strings (the
        flight-recorder overload snapshot)."""
        with self._lock:
            return [n if not c else f"{n}@{c}"
                    for (n, c) in sorted(self._active)]

    def _export(self, name, objective, cluster, value) -> None:
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as M

        labels = {"objective": objective or "", "action": name}
        if cluster:
            labels["cluster"] = cluster
        self.metrics.set_gauge(M.SLO_DEGRADATION, value, labels)

    def _fire(self, name, cluster, which) -> None:
        hooks = self._hooks.get(name)
        if hooks is None or hooks[which] is None:
            return
        try:
            hooks[which](cluster)
        except Exception:
            pass


class OverloadController:
    """The admission gate: limiter slot or bounded cost-aware queue or
    shed.  ``admit(cost)`` is the single seam the webhook wraps its
    review in; the measured time inside is the latency sample the
    limiter adapts on."""

    def __init__(self, config: Optional[OverloadConfig] = None,
                 metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.config = config or OverloadConfig()
        self.metrics = metrics
        self.limiter = AdaptiveLimiter(self.config, metrics=metrics)
        self._clock = clock
        self._sleep = sleep
        self._cv = threading.Condition()
        self._queue_len = 0
        self._queue_cost = 0.0
        self._brownout = 0
        self.shed_count = 0  # total sheds (tests/introspection)
        # optional SLO-burn pressure input (observability/slo.py): a
        # callable -> 0..1 folded into the brownout fill alongside queue
        # pressure, so a burning latency objective can brown out optional
        # work BEFORE the queue itself backs up.  None (the default)
        # keeps the PR 5 behavior bit-identical.
        self._slo_input = None
        # per-tenant / per-priority QoS (resilience/qos.py): when the
        # config carries a QoSConfig the admission queue is the
        # priority-lane DRR queue; None keeps the PR 5 single FIFO
        self._queue_qos = None
        self._tenant_inflight: dict = {}
        # per-priority-lane inflight (QoS mode): the demand-aware
        # assuredConcurrencyShares input — a lane at its assured
        # concurrency yields freed slots to lower lanes with demand
        self._lane_inflight: dict = {}
        self._exported_tenants: set = set()
        self._seq = 0
        self._tenant_cost_input = None
        # the deterministic dequeue/shed trajectory (QoS mode only):
        # ("grant", seq, tenant, priority) / ("shed", seq, tenant,
        # reason) in decision order — identical (config, seed, arrival
        # order) replays it exactly (pinned in tests; /debug/overload
        # reports its length)
        from collections import deque as _deque

        self.trajectory = _deque(maxlen=16384)
        self._ledger_qos = None
        if self.config.qos is not None:
            from gatekeeper_tpu.resilience.qos import (QoSQueue,
                                                       TenantCostLedger)

            self._ledger_qos = TenantCostLedger()
            self._queue_qos = QoSQueue(self.config.qos,
                                       heaviness=self._heaviness,
                                       cap_fn=self._tenant_cap)

    def _tenant_cap(self) -> int:
        """The per-tenant inflight cap in force: the configured
        ``tenantInflightCap`` scaled by the AIMD limiter's CURRENT limit
        over ``max_inflight`` (floor 1).  A cap chosen as a fraction of
        healthy capacity keeps that fraction when the limiter collapses
        — a static 8 over a collapsed limit of 4 would hand one tenant
        every slot and void the isolation guarantee.  0 (no configured
        cap) stays unbounded."""
        cap = self.config.qos.tenant_inflight_cap
        if cap <= 0:
            return 0
        base = max(1, self.config.max_inflight)
        lim = self.limiter.limit
        if lim >= base:
            return cap
        return max(1, (cap * lim + base - 1) // base)

    # --- admission -------------------------------------------------------
    @contextmanager
    def admit(self, cost: float = 1.0, tenant: str = "", priority=None):
        """Admission gate: acquire a limiter slot (immediately or via the
        bounded queue) or raise :class:`Shed`.  The body's wall time is
        the limiter's latency sample.

        ``tenant``/``priority`` (a :class:`qos.PriorityLevel`) engage
        the QoS queue when the controller was built with a QoSConfig
        (see :meth:`route`); with QoS off both are ignored and the path
        is the PR 5 single FIFO, bit-identical."""
        from gatekeeper_tpu.resilience.faults import fault_point

        # the chaos seam for this tier: error mode forces a shed (the
        # failurePolicy plumbing downstream is what's under test);
        # sleep/hang stall the gate like a saturated queue would
        fault_point("webhook.overload",
                    error_factory=lambda spec: Shed(
                        reason="chaos",
                        retry_after_s=spec.delay_s or 1.0))
        if self._queue_qos is None:
            if not self.limiter.try_acquire():
                self._queue_for_slot(cost)  # raises Shed on refusal
        else:
            from gatekeeper_tpu.resilience import qos as _qos

            tenant = tenant or _qos.CLUSTER_TENANT
            if priority is None:
                priority = self.config.qos.classify("", "")
            self._qos_admit(cost, tenant, priority)  # raises Shed
        t0 = self._clock()
        try:
            yield
        finally:
            self.limiter.release(self._clock() - t0)
            with self._cv:
                if self._queue_qos is not None:
                    n = self._tenant_inflight.get(tenant, 0) - 1
                    if n <= 0:
                        self._tenant_inflight.pop(tenant, None)
                    else:
                        self._tenant_inflight[tenant] = n
                    ln = self._lane_inflight.get(priority.name, 0) - 1
                    if ln <= 0:
                        self._lane_inflight.pop(priority.name, None)
                    else:
                        self._lane_inflight[priority.name] = ln
                    self._dispatch_locked()
                    self._pressure_locked()
                    self._cv.notify_all()
                else:
                    self._cv.notify()

    # --- QoS path (resilience/qos.py) ------------------------------------
    def route(self, review_body: dict) -> tuple:
        """(tenant, PriorityLevel) of an AdmissionReview body under the
        active QoS config; ("", None) with QoS off.  The webhook
        handlers call this once and pass the result to :meth:`admit`
        (and to the flight recorder / cost grid as the tenant axis)."""
        if self._queue_qos is None:
            return "", None
        from gatekeeper_tpu.resilience import qos as _qos

        req = review_body.get("request") or {}
        cfg = self.config.qos
        tenant = _qos.tenant_of_request(req, cfg.tenant_key)
        level = cfg.classify(
            req.get("namespace", "") or "",
            ((req.get("userInfo") or {}).get("username", "")) or "")
        return tenant, level

    def _heaviness(self, tenant: str) -> float:
        """Displacement ranking: the internal decayed admitted-cost
        ledger plus (when wired) the PR 8 cost-attribution ``{tenant}``
        axis — "shed the heaviest tenant first" keys on measured cost,
        not arrival order."""
        h = self._ledger_qos.heaviness(tenant) \
            if self._ledger_qos is not None else 0.0
        if self._tenant_cost_input is not None:
            try:
                ext = self._tenant_cost_input() or {}
                # seconds-scale attribution vs bytes-scale ledger: weigh
                # the external axis up so measured eval cost dominates
                # once present
                h += float(ext.get(tenant, 0.0)) * 1e6
            except Exception:
                pass  # attribution must never break admission
        return h

    def set_tenant_cost_input(self, fn) -> None:
        """Wire a per-tenant cost source (callable -> {tenant: cost},
        e.g. ``CostAttribution.tenant_totals``); None disconnects."""
        with self._cv:
            self._tenant_cost_input = fn

    def _qos_admit(self, cost: float, tenant: str, level) -> None:
        from gatekeeper_tpu.resilience.qos import Ticket

        c = self.config
        cap = self._tenant_cap()
        q_depth, q_cost = self._queue_bounds()
        with self._cv:
            t = Ticket(self._seq, tenant, level, cost)
            self._seq += 1
            # fast path: nothing queued ahead, tenant under its cap, a
            # free slot — grant without touching the queue (an idle
            # server admits with zero scheduling overhead)
            if self._queue_qos.depth == 0 and not (
                    cap > 0
                    and self._tenant_inflight.get(tenant, 0) >= cap) \
                    and self.limiter.try_acquire():
                self._grant_locked(t)
                return
            admitted, victim, reason = self._queue_qos.enqueue(
                t, q_depth, q_cost)
            if victim is not None:
                # tenant-aware displacement: the heaviest tenant's
                # newest ticket pays instead of this arrival
                self.trajectory.append(
                    ("shed", victim.seq, victim.tenant, "displaced"))
                self._cv.notify_all()
            if not admitted:
                self.trajectory.append(("shed", t.seq, tenant, reason))
                self._pressure_locked()
                self._shed_locked(reason, tenant=tenant,
                                  priority=level.name)
            self._pressure_locked()
            self._dispatch_locked()
            end = self._clock() + max(0.0, c.queue_timeout_s)
            try:
                while not t.granted and t.shed is None:
                    remaining = end - self._clock()
                    if remaining <= 0:
                        # remove() False means the dispatcher granted or
                        # displaced this ticket concurrently with the
                        # timeout expiry — shedding then would leak the
                        # already-acquired slot; fall through and let
                        # the ticket's own state decide
                        if self._queue_qos.remove(t):
                            self.trajectory.append(
                                ("shed", t.seq, tenant, "queue_timeout"))
                            self._shed_locked("queue_timeout",
                                              tenant=tenant,
                                              priority=level.name)
                        break
                    self._cv.wait(min(remaining, 0.05))
                if t.shed is not None:
                    # displaced while waiting (trajectory already
                    # recorded at the displacement decision)
                    self._shed_locked(t.shed, tenant=tenant,
                                      priority=level.name)
            finally:
                self._pressure_locked()

    def _grant_locked(self, t) -> None:
        t.granted = True
        self._tenant_inflight[t.tenant] = \
            self._tenant_inflight.get(t.tenant, 0) + 1
        self._lane_inflight[t.level.name] = \
            self._lane_inflight.get(t.level.name, 0) + 1
        if self._ledger_qos is not None:
            self._ledger_qos.charge(t.tenant, t.cost)
        self.trajectory.append(
            ("grant", t.seq, t.tenant, t.level.name))

    def _dispatch_locked(self) -> None:
        """Hand freed limiter slots to queued tickets in QoS order:
        strict priority across lanes, DRR across tenants, per-tenant
        inflight caps honored (call under ``_cv``)."""
        q = self._queue_qos
        granted = False
        while q.depth:
            if not self.limiter.try_acquire():
                break
            t = q.pick_next(
                lambda tn: self._tenant_inflight.get(tn, 0),
                lane_inflight_of=lambda nm: self._lane_inflight.get(nm, 0),
                limit=int(self.limiter.limit))
            if t is None:
                # every queued tenant is at its inflight cap: the slot
                # goes back without an AIMD sample
                self.limiter.cancel()
                break
            self._grant_locked(t)
            granted = True
        if granted:
            self._pressure_locked()
            self._cv.notify_all()

    def _queue_bounds(self) -> tuple:
        """(depth, cost) queue bounds in force: the configured bounds,
        halved while the ``shed_harder`` degradation action is active
        (a breaching latency objective's last resort — shed earlier
        instead of queueing deeper).  Inactive = bit-identical."""
        c = self.config
        if degradation_active(SHED_HARDER):
            return max(1, c.queue_depth // 2), c.queue_cost / 2.0
        return c.queue_depth, c.queue_cost

    def _queue_for_slot(self, cost: float) -> None:
        c = self.config
        q_depth, q_cost = self._queue_bounds()
        with self._cv:
            depth_full = self._queue_len + 1 > q_depth
            cost_full = self._queue_cost + cost > q_cost
            if depth_full or cost_full:
                self._shed_locked(
                    "queue_cost" if cost_full and not depth_full
                    else "queue_full")
            self._queue_len += 1
            self._queue_cost += cost
            self._pressure_locked()
            end = self._clock() + max(0.0, c.queue_timeout_s)
            try:
                while True:
                    if self.limiter.try_acquire():
                        return
                    remaining = end - self._clock()
                    if remaining <= 0:
                        self._shed_locked("queue_timeout")
                    self._cv.wait(min(remaining, 0.05))
            finally:
                self._queue_len -= 1
                self._queue_cost -= cost
                self._pressure_locked()

    def _shed_locked(self, reason: str, tenant: str = "",
                     priority: str = "") -> None:
        self.shed_count += 1
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            labels = {"reason": reason}
            # QoS mode: the shed counter grows {tenant, priority} axes
            # (bounded by the registry's cardinality guard); the legacy
            # path keeps the PR 5 {reason}-only labelset bit-identical
            if tenant:
                labels["tenant"] = tenant
            if priority:
                labels["priority"] = priority
            self.metrics.inc_counter(M.OVERLOAD_SHED, labels)
        try:
            from gatekeeper_tpu.utils.logging import log_event

            log_event("warning", "request shed under overload",
                      event_type="overload_shed", reason=reason,
                      queue_depth=self._queue_len,
                      inflight_limit=self.limiter.limit,
                      **({"tenant": tenant} if tenant else {}),
                      **({"priority": priority} if priority else {}))
        except Exception:
            pass
        raise Shed(reason=reason,
                   retry_after_s=self.config.shed_retry_after_s)

    # --- brownout ladder -------------------------------------------------
    def _pressure_locked(self) -> None:
        """Recompute queue fill + brownout level (call under _cv)."""
        c = self.config
        if self._queue_qos is not None:
            # the QoS queue owns depth/cost; mirror into the legacy
            # fields so the ladder math (and its metrics) stay one code
            # path for both modes
            self._queue_len = self._queue_qos.depth
            self._queue_cost = self._queue_qos.cost_total
            self._export_qos_locked()
        fill = 0.0
        if c.queue_depth > 0:
            fill = max(fill, self._queue_len / c.queue_depth)
        if c.queue_cost > 0:
            fill = max(fill, self._queue_cost / c.queue_cost)
        if self._slo_input is not None:
            try:
                fill = max(fill, min(1.0, float(self._slo_input())))
            except Exception:
                pass  # the SLO engine must never break admission
        lvl = self._brownout
        if fill >= c.brownout2_enter or \
                (lvl >= 2 and fill > c.brownout2_exit):
            new = 2
        elif fill >= c.brownout1_enter or \
                (lvl >= 1 and fill > c.brownout1_exit):
            new = 1
        else:
            new = 0
        if new != lvl:
            self._brownout = new
            try:
                from gatekeeper_tpu.utils.logging import log_event

                log_event("warning" if new > lvl else "info",
                          "overload brownout level change",
                          event_type="overload_brownout",
                          brownout_from=lvl, brownout_to=new,
                          queue_fill=round(fill, 3))
            except Exception:
                pass
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.set_gauge(M.OVERLOAD_QUEUE_DEPTH, self._queue_len)
            self.metrics.set_gauge(M.OVERLOAD_BROWNOUT, self._brownout)

    def set_slo_input(self, fn) -> None:
        """Wire an SLO-burn pressure source (callable -> 0..1, e.g.
        ``SLOEngine.pressure``); None disconnects."""
        with self._cv:
            self._slo_input = fn
            self._pressure_locked()

    def set_qos_ledger_clock(self, clock, half_life_s: float) -> None:
        """``--qos-ledger-decay slo-window``: drive the displacement
        ledger's decay from the SLO engine's window clock (totals halve
        per elapsed ``half_life_s``) instead of the event counter —
        "heaviest tenant" then ages on the same timebase the burn-rate
        windows use.  No-op with QoS off; ``clock=None`` restores the
        bit-identical event-count default."""
        with self._cv:
            if self._ledger_qos is not None:
                self._ledger_qos.set_clock(clock, half_life_s)

    def refresh_pressure(self) -> int:
        """Recompute the brownout level outside a queue event (the SLO
        engine calls this each tick so burn changes move the ladder even
        while the queue is idle).  Returns the level."""
        with self._cv:
            self._pressure_locked()
            return self._brownout

    def _export_qos_locked(self) -> None:
        """Per-lane / per-tenant gauges (QoS mode; call under _cv).
        Tenants that left the queue zero out instead of lingering at
        their last value."""
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as M

        q = self._queue_qos
        for lane in q.lanes:
            self.metrics.set_gauge(M.OVERLOAD_LANE_DEPTH, lane.depth(),
                                   {"priority": lane.level.name})
        current = set(q.tenant_cost) | set(self._tenant_inflight)
        for tn in self._exported_tenants - current:
            self.metrics.set_gauge(M.OVERLOAD_TENANT_COST, 0.0,
                                   {"tenant": tn})
            self.metrics.set_gauge(M.OVERLOAD_TENANT_INFLIGHT, 0,
                                   {"tenant": tn})
        for tn in current:
            self.metrics.set_gauge(M.OVERLOAD_TENANT_COST,
                                   q.tenant_cost.get(tn, 0.0),
                                   {"tenant": tn})
            self.metrics.set_gauge(M.OVERLOAD_TENANT_INFLIGHT,
                                   self._tenant_inflight.get(tn, 0),
                                   {"tenant": tn})
        self._exported_tenants = current

    def brownout_level(self) -> int:
        with self._cv:
            return self._brownout

    def queue_depth(self) -> int:
        with self._cv:
            if self._queue_qos is not None:
                return self._queue_qos.depth
            return self._queue_len

    def snapshot(self) -> dict:
        """The ``/debug/overload`` payload: limiter + ladder state, and
        (QoS mode) the full lane view — per-priority queue depths,
        per-tenant queued cost / deficit / weight / inflight, the
        heaviness ranking displacement keys on, and the trajectory
        length (the deterministic dequeue/shed event count)."""
        with self._cv:
            out = {
                "mode": "qos" if self._queue_qos is not None else "fifo",
                "brownout": self._brownout,
                "inflight": self.limiter.inflight,
                "inflight_limit": self.limiter.limit,
                "queue_depth": (self._queue_qos.depth
                                if self._queue_qos is not None
                                else self._queue_len),
                "queue_cost": round(
                    self._queue_qos.cost_total
                    if self._queue_qos is not None
                    else self._queue_cost, 1),
                "shed_count": self.shed_count,
            }
            reg = active_degradations()
            if reg is not None:
                # targeted SLO degradations in force (the /debug/
                # overload + gator triage view of the maps)
                out["degraded"] = reg.active()
            if self._queue_qos is not None:
                cfg = self.config.qos
                out["qos"] = self._queue_qos.snapshot()
                out["qos"]["tenant_inflight"] = dict(self._tenant_inflight)
                out["qos"]["lane_inflight"] = dict(self._lane_inflight)
                out["qos"]["tenant_inflight_cap"] = cfg.tenant_inflight_cap
                out["qos"]["tenant_queue_cost"] = cfg.tenant_queue_cost
                if self._ledger_qos is not None:
                    out["qos"]["tenant_heaviness"] = {
                        t: round(v, 1) for t, v in sorted(
                            self._ledger_qos.totals().items(),
                            key=lambda kv: -kv[1])[:32]}
                out["qos"]["trajectory_len"] = len(self.trajectory)
            return out


# --- activation (mirrors faults.py: process-global + scoped) --------------

_active: list = [None]


def install(controller: Optional[OverloadController]) -> None:
    """Process-global activation (the serving entrypoint)."""
    _active[0] = controller


def uninstall() -> None:
    _active[0] = None


@contextmanager
def activate(controller: OverloadController):
    """Scoped activation for tests; restores the previous controller."""
    prev = _active[0]
    _active[0] = controller
    try:
        yield controller
    finally:
        _active[0] = prev


def active_controller() -> Optional[OverloadController]:
    return _active[0]


def current_brownout() -> int:
    """Brownout level of the installed controller (0 when none) — the
    cheap cross-layer read for optional-work consumers (externaldata
    stale serves, audit device-lane yield)."""
    ctl = _active[0]
    if ctl is None:
        return 0
    return ctl.brownout_level()


# the degradation registry rides the same pattern, separately
# installable: scalar brownout (--slo-brownout) and targeted maps
# (--slo-degradation) compose — consumers OR the two signals
_degradations: list = [None]


def install_degradations(reg: Optional[DegradationRegistry]) -> None:
    """Process-global DegradationRegistry (the serving entrypoint)."""
    _degradations[0] = reg


def uninstall_degradations() -> None:
    _degradations[0] = None


@contextmanager
def activate_degradations(reg: DegradationRegistry):
    """Scoped registry activation for tests."""
    prev = _degradations[0]
    _degradations[0] = reg
    try:
        yield reg
    finally:
        _degradations[0] = prev


def active_degradations() -> Optional[DegradationRegistry]:
    return _degradations[0]


def degradation_active(name: str, cluster: str = "") -> bool:
    """Is the named degradation action in force for this scope?  The
    cheap cross-layer read consumers OR with :func:`current_brownout`
    (False when no registry is installed — bit-identical default)."""
    reg = _degradations[0]
    return reg is not None and reg.is_active(name, cluster)


def yield_device_lane(level: int = 2, max_wait_s: float = 0.25,
                      poll_s: float = 0.01, cluster: str = "") -> float:
    """Brownout level-2 hook for the audit sweep: while the webhook lane
    is under heavy queue pressure, the sweep pauses before submitting its
    next chunk so admission batches win the device.  Bounded by
    ``max_wait_s`` per call — audit degrades, it never stalls.  Returns
    the seconds actually yielded.

    A breaching audit-staleness objective activates
    ``audit_yield_release`` (scoped to ``cluster`` in fleet mode):
    the audit stops ceding the device so it can catch up — staleness
    outranks latency once the staleness objective itself is paging."""
    if degradation_active(AUDIT_YIELD_RELEASE, cluster):
        return 0.0
    ctl = _active[0]
    if ctl is None or ctl.brownout_level() < level:
        return 0.0
    waited = 0.0
    while waited < max_wait_s and ctl.brownout_level() >= level:
        ctl._sleep(poll_s)
        waited += poll_s
    if waited and ctl.metrics is not None:
        from gatekeeper_tpu.metrics import registry as M

        ctl.metrics.inc_counter(
            M.RESILIENCE_DEGRADED,
            {"component": "audit", "to": "device_lane_yield"})
    return waited


# --- graceful drain -------------------------------------------------------

SERVING, DRAINING, STOPPED = "serving", "draining", "stopped"


class DrainCoordinator:
    """The shutdown state machine: ``serving → draining → stopped``.

    ``begin()`` is idempotent and first-caller-wins (SIGTERM may arrive
    twice); readiness checks gate on :attr:`draining` so the LB pulls
    the pod before the listener closes.  ``finish()`` records the drain
    duration into ``gatekeeper_drain_seconds``."""

    def __init__(self, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._state = SERVING
        self._begun_at: Optional[float] = None
        self.drain_seconds: Optional[float] = None
        self._stopped = threading.Event()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._state != SERVING

    def begin(self, reason: str = "") -> bool:
        """Enter DRAINING; True for the first caller only."""
        with self._lock:
            if self._state != SERVING:
                return False
            self._state = DRAINING
            self._begun_at = self._clock()
        try:
            from gatekeeper_tpu.utils.logging import log_event

            log_event("info", "graceful drain started",
                      event_type="drain_started", reason=reason)
        except Exception:
            pass
        return True

    def finish(self) -> float:
        """Enter STOPPED; records and returns the drain duration."""
        with self._lock:
            if self._state == STOPPED:
                return self.drain_seconds or 0.0
            dt = (self._clock() - self._begun_at
                  if self._begun_at is not None else 0.0)
            self._state = STOPPED
            self.drain_seconds = dt
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.set_gauge(M.DRAIN_SECONDS, dt)
        self._stopped.set()
        return dt

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)
