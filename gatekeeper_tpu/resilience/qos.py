"""Per-tenant, per-priority admission QoS for the overload path.

PR 5's admission gate holds waiting requests in ONE cost-aware FIFO; at
millions-of-users scale a single noisy tenant posting 2MB ConfigMaps
starves kube-system and break-glass traffic — the exact failure mode
kube-apiserver's API Priority & Fairness (APF) exists to solve.  This
module is the APF-shaped replacement the :class:`OverloadController`
mounts when ``--qos on``:

- **Priority lanes** (:class:`PriorityLevel`, configured by a
  ``--qos-config`` JSON mirroring APF's PriorityLevelConfiguration
  shape): strict-priority dequeue across lanes, so system / break-glass
  namespaces are always served ahead of user traffic and shed last.
- **Weighted-fair dequeue across tenants** (:class:`QoSQueue`): within
  a lane, tenants (namespace or serviceaccount, per ``tenantKey``) are
  scheduled by deficit round robin — each visit credits
  ``quantum × weight`` and a ticket is served when the tenant's deficit
  covers its admission cost, so weights hold in COST units even under
  heavily skewed object sizes (a tenant of 2MB ConfigMaps gets the same
  byte share as a tenant of 2KB Pods, not the same request share).
- **Per-tenant inflight caps and queue-cost budgets**: one tenant can
  neither occupy every limiter slot nor fill the shared queue.
- **Tenant-aware displacement**: when the queue overflows, the shed
  target is the newest queued ticket of the HEAVIEST tenant (decayed
  admitted-cost ledger, optionally fed by the PR 8 cost-attribution
  ``{tenant}`` axis) in the lowest-priority lane — not whoever happens
  to arrive mid-burst — and only if the newcomer outranks it.

Everything here is deterministic: scheduling state advances only on
(enqueue, pick, release) events, the ledger decays by event count, and
ties break lexicographically — identical (config, seed, arrival order)
replays the exact dequeue/shed trajectory (pinned in tests).  The
module is lock-free by design: every method is called under the
OverloadController's condition-variable lock.
"""

from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

TENANT_NAMESPACE = "namespace"
TENANT_SERVICEACCOUNT = "serviceaccount"

# tenant label for cluster-scoped objects / anonymous users: every
# request maps to SOME tenant so the fairness math has no escape hatch
CLUSTER_TENANT = "_cluster"


@dataclass(frozen=True)
class PriorityLevel:
    """One APF-shaped priority lane.  ``order`` is the dequeue rank
    (lower dequeues first, sheds last); a level with no selectors is a
    catch-all.

    ``shares`` (APF's ``assuredConcurrencyShares``) makes dequeue
    demand-aware: when ANY level declares shares > 0, a lane already
    holding its assured fraction of the limiter (``ceil(limit x shares /
    sum shares)``) yields freed slots to lower-priority lanes with
    queued demand — so a pathological system-lane flood is bounded too,
    instead of starving user traffic forever under strict priority.
    All-zero shares (the default) keeps strict priority bit-identical."""

    name: str
    order: int
    namespaces: tuple = ()
    namespace_prefixes: tuple = ()
    users: tuple = ()
    user_prefixes: tuple = ()
    shares: int = 0

    def matches(self, namespace: str, username: str) -> bool:
        if not (self.namespaces or self.namespace_prefixes
                or self.users or self.user_prefixes):
            return True  # catch-all
        if namespace and namespace in self.namespaces:
            return True
        if namespace and any(namespace.startswith(p)
                             for p in self.namespace_prefixes):
            return True
        if username and username in self.users:
            return True
        if username and any(username.startswith(p)
                            for p in self.user_prefixes):
            return True
        return False


def default_levels() -> list:
    """The built-in lane set (used when --qos-config names none):
    system traffic (kube-system / gatekeeper's own namespace / node and
    apiserver identities) ahead of break-glass ahead of everyone."""
    return [
        PriorityLevel(
            name="system", order=0,
            namespaces=("kube-system", "gatekeeper-system"),
            namespace_prefixes=("kube-",),
            user_prefixes=("system:node:", "system:apiserver",
                           "system:kube-")),
        PriorityLevel(
            name="break-glass", order=10,
            namespace_prefixes=("break-glass",),
            user_prefixes=("break-glass:",)),
        PriorityLevel(name="user", order=100),
    ]


@dataclass
class QoSConfig:
    """Parsed ``--qos-config`` (see :func:`load_qos_config`)."""

    tenant_key: str = TENANT_NAMESPACE
    levels: list = field(default_factory=default_levels)
    tenant_weights: dict = field(default_factory=dict)
    default_weight: float = 1.0
    # 0 disables the bound
    tenant_inflight_cap: int = 0
    tenant_queue_cost: float = 0.0
    # DRR credit per ring visit for a weight-1 tenant, in admission-cost
    # units (object bytes x matched constraints); sized near a typical
    # small object so byte-skew fairness engages within a few visits
    quantum: float = 16384.0

    def weight(self, tenant: str) -> float:
        return max(1e-9, float(
            self.tenant_weights.get(tenant, self.default_weight)))

    def classify(self, namespace: str, username: str) -> PriorityLevel:
        for lv in self.levels:
            if lv.matches(namespace, username):
                return lv
        return self.levels[-1]


def load_qos_config(path: str) -> QoSConfig:
    """Parse a ``--qos-config`` JSON file.  Shape (every field
    optional, mirroring APF's PriorityLevelConfiguration spirit)::

        {"tenantKey": "namespace" | "serviceaccount",
         "priorityLevels": [
           {"name": "system",
            "matchNamespaces": ["kube-system"],
            "matchNamespacePrefixes": ["kube-"],
            "matchUsers": [], "matchUserPrefixes": ["system:node:"]},
           {"name": "user"}],          # no selectors = catch-all
         "tenantWeights": {"team-a": 4},
         "defaultTenantWeight": 1,
         "tenantInflightCap": 8,
         "tenantQueueCost": 64000000,
         "quantum": 16384}

    Lane order is list position (first = highest priority, sheds
    last)."""
    with open(path) as f:
        doc = json.load(f)
    return parse_qos_config(doc)


def parse_qos_config(doc: dict) -> QoSConfig:
    cfg = QoSConfig()
    key = doc.get("tenantKey", cfg.tenant_key)
    if key not in (TENANT_NAMESPACE, TENANT_SERVICEACCOUNT):
        raise ValueError(f"qos tenantKey must be {TENANT_NAMESPACE}|"
                         f"{TENANT_SERVICEACCOUNT}, got {key!r}")
    cfg.tenant_key = key
    raw_levels = doc.get("priorityLevels") or []
    if raw_levels:
        levels = []
        for i, lv in enumerate(raw_levels):
            levels.append(PriorityLevel(
                name=str(lv.get("name") or f"level{i}"),
                order=int(lv.get("order", i * 10)),
                namespaces=tuple(lv.get("matchNamespaces") or ()),
                namespace_prefixes=tuple(
                    lv.get("matchNamespacePrefixes") or ()),
                users=tuple(lv.get("matchUsers") or ()),
                user_prefixes=tuple(lv.get("matchUserPrefixes") or ()),
                shares=int(lv.get("assuredConcurrencyShares", 0)),
            ))
        levels.sort(key=lambda l: (l.order, l.name))
        cfg.levels = levels
    cfg.tenant_weights = {str(k): float(v) for k, v in
                          (doc.get("tenantWeights") or {}).items()}
    cfg.default_weight = float(doc.get("defaultTenantWeight", 1.0))
    cfg.tenant_inflight_cap = int(doc.get("tenantInflightCap", 0))
    cfg.tenant_queue_cost = float(doc.get("tenantQueueCost", 0.0))
    cfg.quantum = float(doc.get("quantum", cfg.quantum))
    return cfg


_SA_PREFIX = "system:serviceaccount:"


def normalize_serviceaccount(username: str) -> Optional[str]:
    """The canonical ``system:serviceaccount:<ns>:<name>`` triple, or
    None when ``username`` is not a well-formed serviceaccount identity.

    ``userInfo.username`` is attacker-influenced on impersonation /
    proxy paths, so the serviceaccount tenant key must not trust it
    verbatim: only an EXACT case-sensitive prefix with exactly two
    non-empty, whitespace-free segments (k8s namespace/SA names — ':'
    is not legal in either) normalizes; anything else (extra segments,
    empty parts, case games like ``System:ServiceAccount:...``) is not
    a serviceaccount and must not be billed as one."""
    if not username.startswith(_SA_PREFIX):
        return None
    rest = username[len(_SA_PREFIX):]
    parts = rest.split(":")
    if len(parts) != 2:
        return None
    ns, name = parts
    if not ns or not name:
        return None
    if ns != ns.strip() or name != name.strip() or " " in ns or \
            " " in name:
        return None
    return _SA_PREFIX + ns + ":" + name


def tenant_of_request(req: dict, tenant_key: str = TENANT_NAMESPACE,
                      cluster: str = "") -> str:
    """Tenant identity of an AdmissionReview ``request`` dict — the
    attribution key shared by QoS, the flight recorder and the cost
    grid's ``{tenant}`` axis.  Under the serviceaccount key, SA-shaped
    usernames normalize through :func:`normalize_serviceaccount`;
    malformed SA triples fold into the cluster tenant (a spoofed-looking
    identity must not mint itself a fresh fair-share queue), and non-SA
    users keep their username.

    ``cluster`` (fleet mode) prefixes the tenant with the serving
    cluster's id — the cluster → tenant → priority routing key: every
    cluster's namespaces get their OWN fair-share queues (``team-a`` on
    cluster-1 and ``team-a`` on cluster-2 are different tenants, with
    independent DRR deficits, inflight caps and displacement ledgers),
    while priority classification stays request-derived — one cluster's
    user flood ranks below every cluster's system lane and can never
    displace it."""
    if tenant_key == TENANT_SERVICEACCOUNT:
        user = ((req.get("userInfo") or {}).get("username", "")) or ""
        if not user:
            tenant = CLUSTER_TENANT
        elif user.lower().startswith(_SA_PREFIX) or \
                user.startswith(_SA_PREFIX):
            tenant = normalize_serviceaccount(user) or CLUSTER_TENANT
        else:
            tenant = user
    else:
        ns = req.get("namespace", "") or ""
        tenant = ns or CLUSTER_TENANT
    return f"{cluster}:{tenant}" if cluster else tenant


class TenantCostLedger:
    """Decayed per-tenant admitted-cost totals — the "who is heaviest"
    input for displacement.  Decay is by EVENT COUNT (every
    ``half_every`` charges all totals halve), not wall time, so a
    replayed admission sequence reproduces the exact heaviness
    trajectory.

    ``set_clock`` (``--qos-ledger-decay slo-window``) arms the optional
    WALL-WINDOW decay driver instead: totals halve once per elapsed
    ``half_life_s`` of the supplied clock — the SLO engine's window
    clock, so "heaviest" ages on the same timebase the burn-rate
    windows use, and an idle gap forgets a past burst the way a burn
    window does (event-count decay can hold a dead tenant heavy
    forever when traffic stops).  Unarmed (the default) the ledger is
    bit-identical to the event-count behavior."""

    def __init__(self, half_every: int = 512):
        self.half_every = max(1, int(half_every))
        self._cost: dict = {}
        self._n = 0
        # wall-window decay driver (None = event-count decay)
        self._clock = None
        self._half_life_s = 0.0
        self._last_half = 0.0

    def set_clock(self, clock, half_life_s: float) -> None:
        """Arm (or, with ``clock=None``, disarm) wall-window decay."""
        if clock is None or half_life_s <= 0:
            self._clock = None
            self._half_life_s = 0.0
            return
        self._clock = clock
        self._half_life_s = float(half_life_s)
        self._last_half = clock()

    def _halve(self) -> None:
        self._cost = {t: c / 2.0 for t, c in self._cost.items()
                      if c / 2.0 > 1.0}

    def charge(self, tenant: str, cost: float) -> None:
        if self._clock is not None:
            # elapsed windows predate this charge: decay FIRST, then
            # land the new cost at full weight
            now = self._clock()
            while now - self._last_half >= self._half_life_s:
                self._halve()
                self._last_half += self._half_life_s
                if not self._cost:
                    # nothing left to decay: snap the window forward so
                    # a long idle gap costs O(1), not O(gap/half_life)
                    self._last_half = now
                    break
            self._cost[tenant] = self._cost.get(tenant, 0.0) \
                + max(0.0, cost)
            return
        self._cost[tenant] = self._cost.get(tenant, 0.0) + max(0.0, cost)
        self._n += 1
        if self._n % self.half_every == 0:
            self._halve()

    def totals(self) -> dict:
        return dict(self._cost)

    def heaviness(self, tenant: str) -> float:
        return self._cost.get(tenant, 0.0)


class Ticket:
    """One queued admission waiting for a limiter slot."""

    __slots__ = ("seq", "tenant", "level", "cost", "granted", "shed")

    def __init__(self, seq: int, tenant: str, level: PriorityLevel,
                 cost: float):
        self.seq = seq
        self.tenant = tenant
        self.level = level
        self.cost = cost
        self.granted = False
        self.shed: Optional[str] = None  # shed reason once decided


class _Lane:
    """Per-priority-level DRR state: tenant FIFOs, the tenant ring in
    activation order, deficits, and the rotating ring index."""

    __slots__ = ("level", "queues", "ring", "deficit", "rr")

    def __init__(self, level: PriorityLevel):
        self.level = level
        self.queues: dict = {}  # tenant -> deque[Ticket]
        self.ring: list = []  # active tenants, activation order
        self.deficit: dict = {}
        self.rr = 0

    def depth(self) -> int:
        return sum(len(q) for q in self.queues.values())


class QoSQueue:
    """The priority-lane + deficit-round-robin admission queue.

    All methods must be called under the owning controller's lock; the
    queue itself is pure state + deterministic decisions."""

    def __init__(self, config: QoSConfig,
                 heaviness: Optional[Callable[[str], float]] = None,
                 cap_fn: Optional[Callable[[], int]] = None):
        self.config = config
        self._heaviness = heaviness or (lambda tenant: 0.0)
        # live per-tenant inflight cap (PR 10 NEXT): the owning
        # controller derives it from the AIMD limiter's CURRENT limit so
        # isolation survives limit collapse — a static cap of 8 over a
        # collapsed limit of 4 would let one tenant own every slot.
        # None keeps the static config cap.
        self._cap_fn = cap_fn
        self.lanes = [_Lane(lv) for lv in config.levels]
        self._by_level = {lv.name: lane
                          for lv, lane in zip(config.levels, self.lanes)}
        self.depth = 0
        self.cost_total = 0.0
        self.tenant_cost: dict = {}  # queued cost per tenant, all lanes
        # demand-aware shares engage only when some level declares them
        # (all-zero keeps the strict-priority dequeue bit-identical,
        # including the seeded trajectory pins)
        self._shares_total = sum(max(0, lv.shares)
                                 for lv in config.levels)

    def assured_cap(self, level: PriorityLevel, limit: int) -> int:
        """APF assured-concurrency value of one lane under the CURRENT
        limiter limit: ``ceil(limit x shares / sum shares)``, floor 1.
        0 = the lane declared no shares (unbounded under strict
        priority)."""
        if self._shares_total <= 0 or level.shares <= 0 or limit <= 0:
            return 0
        return max(1, -(-limit * level.shares // self._shares_total))

    def effective_cap(self) -> int:
        """The per-tenant inflight cap in force NOW (0 = unbounded)."""
        if self._cap_fn is not None:
            return self._cap_fn()
        return self.config.tenant_inflight_cap

    # --- enqueue / shed ordering ---------------------------------------
    def enqueue(self, t: Ticket, queue_depth: int, queue_cost: float
                ) -> tuple:
        """Admit ``t`` to its lane or decide a shed.  Returns
        ``(admitted, victim, reason)``: ``admitted`` False means the
        NEWCOMER sheds with ``reason``; a non-None ``victim`` is a
        previously queued ticket displaced to make room (its waiter
        sheds with reason ``displaced``)."""
        c = self.config
        if c.tenant_queue_cost > 0 and \
                self.tenant_cost.get(t.tenant, 0.0) + t.cost \
                > c.tenant_queue_cost:
            return False, None, "tenant_queue_cost"
        # bound semantics mirror the PR 5 FIFO exactly: 0 is a
        # zero-capacity queue (every queued arrival overflows), not
        # "unlimited"
        depth_full = self.depth + 1 > queue_depth
        cost_full = self.cost_total + t.cost > queue_cost
        victim = None
        if depth_full or cost_full:
            victim = self._displacement_victim(t)
            if victim is None:
                return False, None, \
                    "queue_cost" if cost_full and not depth_full \
                    else "queue_full"
            self.remove(victim)
            victim.shed = "displaced"
        self._push(t)
        return True, victim, ""

    def _load(self, tenant: str) -> float:
        """Displacement weight of a tenant: measured admitted cost (the
        decayed ledger, optionally cost-attribution-fed) PLUS its
        currently queued demand — a burst's queued wall makes its
        tenant "heaviest" immediately, before the ledger has learned
        anything about it."""
        return self._heaviness(tenant) + self.tenant_cost.get(tenant, 0.0)

    def _displacement_victim(self, newcomer: Ticket) -> Optional[Ticket]:
        """Tenant-aware shed ordering: from the LOWEST-priority nonempty
        lane, the newest queued ticket of the heaviest tenant — and only
        if the newcomer outranks it (higher lane, or same lane and a
        strictly lighter tenant).  System lanes therefore shed last, and
        the mid-burst arrival order stops deciding who pays."""
        for lane in reversed(self.lanes):
            if lane.depth() == 0:
                continue
            victim_lv = lane.level
            if newcomer.level.order > victim_lv.order:
                return None  # newcomer ranks below every queued ticket
            # heaviest tenant in this lane; ties break lexicographically
            # (deterministic replay)
            tenant = max(
                (tn for tn in lane.queues if lane.queues[tn]),
                key=lambda tn: (self._load(tn), tn))
            if newcomer.level.order == victim_lv.order:
                same = tenant == newcomer.tenant
                if same or self._load(newcomer.tenant) >= \
                        self._load(tenant):
                    return None  # not lighter: the newcomer pays
            return lane.queues[tenant][-1]
        return None

    def _push(self, t: Ticket) -> None:
        lane = self._by_level[t.level.name]
        q = lane.queues.get(t.tenant)
        if q is None:
            q = lane.queues[t.tenant] = deque()
        if t.tenant not in lane.ring:
            lane.ring.append(t.tenant)
            lane.deficit.setdefault(t.tenant, 0.0)
        q.append(t)
        self.depth += 1
        self.cost_total += t.cost
        self.tenant_cost[t.tenant] = \
            self.tenant_cost.get(t.tenant, 0.0) + t.cost

    def remove(self, t: Ticket) -> bool:
        """Drop a queued ticket (timeout, displacement)."""
        lane = self._by_level[t.level.name]
        q = lane.queues.get(t.tenant)
        if q is None or t not in q:
            return False
        q.remove(t)
        self._account_out(t, lane)
        return True

    def _account_out(self, t: Ticket, lane: _Lane) -> None:
        self.depth -= 1
        self.cost_total -= t.cost
        nc = self.tenant_cost.get(t.tenant, 0.0) - t.cost
        if nc <= 1e-9:
            self.tenant_cost.pop(t.tenant, None)
        else:
            self.tenant_cost[t.tenant] = nc
        if not lane.queues.get(t.tenant):
            lane.queues.pop(t.tenant, None)
            lane.deficit.pop(t.tenant, None)
            if t.tenant in lane.ring:
                # keep rr pointing at the ring element after the removed
                # tenant so rotation order survives membership churn
                idx = lane.ring.index(t.tenant)
                pos = lane.rr % len(lane.ring)
                lane.ring.pop(idx)
                if idx < pos:
                    pos -= 1
                lane.rr = pos % len(lane.ring) if lane.ring else 0

    # --- weighted-fair dequeue -----------------------------------------
    def pick_next(self, inflight_of: Callable[[str], int],
                  lane_inflight_of: Optional[Callable[[str], int]] = None,
                  limit: int = 0) -> Optional[Ticket]:
        """The next ticket to grant a freed limiter slot: strict
        priority across lanes; deficit round robin across tenants within
        a lane (credit ``quantum x weight`` per unaffordable visit,
        serve when the deficit covers the head's cost); tenants at the
        per-tenant inflight cap are skipped without losing their turn.
        Returns None when nothing is serviceable (empty, or every queued
        tenant is at its cap).

        With ``assuredConcurrencyShares`` configured (and the caller
        supplying per-lane inflight + the live limit), a lane already at
        its assured concurrency yields the slot to a lower-priority lane
        with queued demand — then a work-conserving second pass hands it
        back if nothing below could actually take it."""
        if self._shares_total > 0 and lane_inflight_of is not None \
                and limit > 0:
            for li, lane in enumerate(self.lanes):
                if lane.depth() == 0:
                    continue
                cap = self.assured_cap(lane.level, limit)
                if cap and lane_inflight_of(lane.level.name) >= cap and \
                        any(l2.depth() for l2 in self.lanes[li + 1:]):
                    continue  # bounded: lower-priority demand goes first
                t = self._pick_lane(lane, inflight_of)
                if t is not None:
                    return t
        for lane in self.lanes:
            t = self._pick_lane(lane, inflight_of)
            if t is not None:
                return t
        return None

    def _serviceable(self, lane: _Lane, tenant: str,
                     inflight_of: Callable[[str], int]) -> bool:
        if not lane.queues.get(tenant):
            return False
        cap = self.effective_cap()
        return not (cap > 0 and inflight_of(tenant) >= cap)

    def _pick_lane(self, lane: _Lane,
                   inflight_of: Callable[[str], int]) -> Optional[Ticket]:
        ring = lane.ring
        ok = [tn for tn in ring
              if self._serviceable(lane, tn, inflight_of)]
        if not ok:
            return None
        # bounded search: every full ring rotation credits each
        # serviceable tenant once, so the costliest head is affordable
        # within ceil(max_cost / (quantum x min_weight)) rotations
        max_cost = max(lane.queues[tn][0].cost for tn in ok)
        min_w = min(self.config.weight(tn) for tn in ok)
        rotations = int(max_cost / (self.config.quantum * min_w)) + 2
        for _ in range(rotations * len(ring)):
            tn = ring[lane.rr % len(ring)]
            if not self._serviceable(lane, tn, inflight_of):
                lane.rr += 1
                continue
            head = lane.queues[tn][0]
            if lane.deficit.get(tn, 0.0) >= head.cost:
                lane.deficit[tn] = lane.deficit.get(tn, 0.0) - head.cost
                lane.queues[tn].popleft()
                # rr stays on this tenant: remaining deficit serves its
                # next head first (classic DRR spends the round's credit)
                self._account_out(head, lane)
                return head
            lane.deficit[tn] = lane.deficit.get(tn, 0.0) + \
                self.config.quantum * self.config.weight(tn)
            lane.rr += 1
        return None  # unreachable: the rotation bound always affords

    # --- introspection ---------------------------------------------------
    def snapshot(self) -> dict:
        """The ``/debug/overload`` lane view."""
        lanes = []
        for lane in self.lanes:
            tenants = {
                tn: {"queued": len(q),
                     "queued_cost": round(sum(t.cost for t in q), 1),
                     "deficit": round(lane.deficit.get(tn, 0.0), 1),
                     "weight": self.config.weight(tn)}
                for tn, q in sorted(lane.queues.items()) if q}
            lanes.append({
                "priority": lane.level.name,
                "order": lane.level.order,
                "shares": lane.level.shares,
                "queued": lane.depth(),
                "tenants": tenants,
            })
        return {
            "tenant_key": self.config.tenant_key,
            "queued": self.depth,
            "queued_cost": round(self.cost_total, 1),
            # the cap in force NOW (AIMD-derived when the limiter has
            # collapsed below max_inflight; 0 = unbounded)
            "tenant_inflight_cap": self.effective_cap(),
            "lanes": lanes,
        }


def qos_from_args(qos: str, qos_config: str) -> Optional[QoSConfig]:
    """CLI plumbing: ``--qos off`` (the compat default) returns None —
    the controller keeps the PR 5 single-FIFO path bit-identical;
    ``--qos on`` loads ``--qos-config`` or the built-in lane set."""
    if qos != "on":
        return None
    if qos_config:
        return load_qos_config(qos_config)
    return QoSConfig()
