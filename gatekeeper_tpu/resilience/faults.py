"""Deterministic, seeded fault-injection seam (the chaos harness).

Production serving stacks prove their failure paths by *injecting* the
failures, not by waiting for them (the chaos-seam discipline of
fault-tolerant serving systems; cf. PAPERS.md entries on gray-failure
detection).  This module is the single seam: code on a dangerous boundary
calls :func:`fault_point` with a dotted site name —

    fault_point("externaldata.send", provider=name)

— and, with no active plan (the default), that call is one contextvar
read plus one global read: nanoseconds, no locks, no behavior change.
With a plan active, matching specs fire deterministically (seeded RNG,
count-based gates — the same spec file replays the same fault sequence).

Sites threaded through the stack:

- ``webhook.review``        the admission review path (policy.py)
- ``externaldata.send``     provider transport (externaldata/providers.py)
- ``kube.request``          every apiserver HTTP call (sync/kube.py)
- ``pipeline.stage.<name>`` each staged-pipeline worker (pipeline/executor.py)
- ``device.dispatch``       TPU driver batch dispatch (drivers/tpu_driver.py,
                            parallel/sharded.py)

Modes: ``sleep`` (added latency), ``hang`` (a long stall — deadline
budgets must cut it), ``error`` (raise; sites may map the spec onto their
own exception type via ``error_factory``, e.g. an apiserver 500), and
``partial`` (returned to the caller — only sites that understand partial
responses act on it; everyone else is unaffected).

Activation: :func:`inject` (contextvar-scoped, for tests and per-request
scoping), :func:`install` (process-global, the ``--chaos spec.json`` CLI
flag — worker threads spawned before the contextvar was set still see
it).  Every fired injection counts into
``gatekeeper_resilience_faults_injected_count{site,mode}`` and emits a
structured log line.
"""

from __future__ import annotations

import contextvars
import fnmatch
import json
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class FaultError(Exception):
    """The default injected exception (error-mode faults with no
    site-supplied ``error_factory``)."""


@dataclass
class FaultSpec:
    """One injection rule.  ``site`` is an fnmatch pattern
    (``pipeline.stage.*`` matches every stage worker)."""

    site: str
    mode: str = "error"  # sleep | hang | error | partial
    delay_s: float = 0.05  # sleep duration; hang defaults to 30s if unset
    error: str = "injected fault"
    status: int = 500  # error-mode hint for HTTP-shaped sites (kube)
    times: int = -1  # fire at most N times (-1 = unlimited)
    after: int = 0  # skip the first N matching calls
    every: int = 1  # then fire on every Nth matching call
    probability: float = 1.0  # gated by the plan's seeded RNG when < 1
    # partial-mode payload hint (e.g. fraction of keys a provider returns)
    fraction: float = 0.5

    @staticmethod
    def from_dict(d: dict) -> "FaultSpec":
        known = {f for f in FaultSpec.__dataclass_fields__}
        bad = set(d) - known
        if bad:
            raise ValueError(f"chaos spec: unknown fault fields {sorted(bad)}")
        if "site" not in d:
            raise ValueError("chaos spec: fault entry needs a 'site'")
        spec = FaultSpec(**d)
        if spec.mode not in ("sleep", "hang", "error", "partial"):
            raise ValueError(f"chaos spec: unknown mode {spec.mode!r}")
        return spec


@dataclass
class FaultAction:
    """What a fired spec asks the site to do.  Returned from
    :func:`fault_point` ONLY for partial mode (sleep/hang/error are
    executed inside the seam); callers that ignore the return value are
    transparently unaffected by partial specs."""

    mode: str
    spec: FaultSpec
    site: str


class FaultPlan:
    """A set of specs + deterministic firing state.

    The same (specs, seed) pair replays the same fault sequence for the
    same sequence of ``fault_point`` calls — chaos runs are reproducible
    and differential-testable."""

    def __init__(self, specs, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        self.specs = [s if isinstance(s, FaultSpec) else
                      FaultSpec.from_dict(s) for s in (specs or [])]
        self.seed = seed
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._lock = threading.Lock()
        self._calls: dict = {}  # spec idx -> matching-call count
        self._fired: dict = {}  # spec idx -> fired count
        self.events: list = []  # [(site, mode, n_fired)] in fire order

    # --- introspection ---------------------------------------------------
    def fired(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is None:
                return len(self.events)
            return sum(1 for s, _m, _n in self.events if s == site)

    # --- the hot path ----------------------------------------------------
    def check(self, site: str) -> Optional[FaultAction]:
        """Return the action to take at ``site`` (None = no fault).  Count
        and RNG state advance under the lock so concurrent sites fire
        deterministically *per spec* (firing order across threads is the
        arrival order of the calls)."""
        action = None
        for i, spec in enumerate(self.specs):
            if not fnmatch.fnmatch(site, spec.site):
                continue
            with self._lock:
                n = self._calls.get(i, 0)
                self._calls[i] = n + 1
                if n < spec.after:
                    continue
                if spec.every > 1 and (n - spec.after) % spec.every != 0:
                    continue
                fired = self._fired.get(i, 0)
                if spec.times >= 0 and fired >= spec.times:
                    continue
                if spec.probability < 1.0 and \
                        self._rng.random() >= spec.probability:
                    continue
                self._fired[i] = fired + 1
                self.events.append((site, spec.mode, fired + 1))
            action = FaultAction(spec.mode, spec, site)
            break  # first matching spec wins
        return action

    def sleep_for(self, action: FaultAction) -> None:
        d = action.spec.delay_s
        if action.mode == "hang" and d <= 0.05:
            d = 30.0  # a hang with no explicit delay is a long stall
        self._sleep(d)


# --- activation ----------------------------------------------------------

_ctx_plan: contextvars.ContextVar = contextvars.ContextVar(
    "gatekeeper_fault_plan", default=None)
_global_plan: list = [None]  # process-scoped (CLI --chaos; worker threads)
_metrics: list = [None]  # MetricsRegistry sink for fired injections


def set_metrics_registry(registry) -> None:
    """Route fired-injection counters into a MetricsRegistry
    (``gatekeeper_resilience_faults_injected_count{site,mode}``)."""
    _metrics[0] = registry


def install(plan: Optional[FaultPlan]) -> None:
    """Process-global activation (the ``--chaos spec.json`` flag): every
    thread sees the plan, including workers spawned before the call."""
    _global_plan[0] = plan


def uninstall() -> None:
    _global_plan[0] = None


@contextmanager
def inject(plan: FaultPlan, process: bool = True):
    """Scoped activation for tests: sets the contextvar (same-thread
    sites) and — by default — the process-global too, so sites running on
    worker threads (batcher, pipeline stages, watch loops) observe the
    plan.  Restores both on exit."""
    token = _ctx_plan.set(plan)
    prev = _global_plan[0]
    if process:
        _global_plan[0] = plan
    try:
        yield plan
    finally:
        _ctx_plan.reset(token)
        if process:
            _global_plan[0] = prev


def active_plan() -> Optional[FaultPlan]:
    plan = _ctx_plan.get()
    if plan is None:
        plan = _global_plan[0]
    return plan


def load_chaos_spec(path_or_dict) -> FaultPlan:
    """Parse a ``--chaos`` spec: ``{"seed": 0, "faults": [{...}, ...]}``
    (see README "Failure semantics" for the format)."""
    doc = path_or_dict
    if isinstance(doc, str):
        with open(doc) as f:
            doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError("chaos spec must be a JSON object")
    return FaultPlan(doc.get("faults", []), seed=int(doc.get("seed", 0)))


# --- the injection point -------------------------------------------------

def fault_point(site: str,
                error_factory: Optional[Callable[[FaultSpec], BaseException]]
                = None,
                **ctx: Any) -> Optional[FaultAction]:
    """Injection seam.  No active plan: near-zero cost, returns None.

    With a plan: sleep/hang stall here, error raises here (through
    ``error_factory`` when the site maps faults onto its own exception
    type), partial returns the action for the site to interpret.  ``ctx``
    rides into the structured log line only."""
    plan = _ctx_plan.get()
    if plan is None:
        plan = _global_plan[0]
        if plan is None:
            return None
    action = plan.check(site)
    if action is None:
        return None
    _record(site, action.mode, ctx)
    if action.mode in ("sleep", "hang"):
        plan.sleep_for(action)
        return None
    if action.mode == "error":
        exc = (error_factory(action.spec) if error_factory is not None
               else FaultError(f"{site}: {action.spec.error}"))
        raise exc
    return action  # partial


def _record(site: str, mode: str, ctx: dict) -> None:
    reg = _metrics[0]
    if reg is not None:
        from gatekeeper_tpu.metrics import registry as M

        reg.inc_counter(M.RESILIENCE_FAULTS,
                        {"site": site, "mode": mode})
    try:
        from gatekeeper_tpu.observability import tracing

        # a --chaos run with --trace shows exactly where each fault
        # landed: the injection becomes an event on the ambient span
        tracing.add_event("fault_injected", site=site, mode=mode)
    except Exception:
        pass
    try:
        from gatekeeper_tpu.utils.logging import log_event

        log_event("info", "fault injected", event_type="fault_injected",
                  fault_site=site, fault_mode=mode,
                  **{f"fault_{k}": str(v) for k, v in ctx.items()})
    except Exception:
        pass  # the chaos seam must never add a failure mode of its own
