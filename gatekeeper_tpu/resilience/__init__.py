"""Resilience layer: fault-injection seam + deadline/retry/breaker policies
+ overload protection.

``faults`` is the deterministic chaos seam (contextvar-scoped injection
points threaded through the webhook, external-data, apiserver, pipeline
and device-dispatch paths); ``policy`` is the unified failure-handling
layer (deadline budgets, jittered exponential retry, per-dependency
circuit breakers, graceful-degradation hooks); ``overload`` is the
self-protection tier (AIMD adaptive concurrency, cost-aware load
shedding, brownout ladder, graceful-drain state machine).  Every
injection, retry, breaker transition, deadline miss, shed and brownout
flows into the metrics registry (``gatekeeper_resilience_*`` /
``gatekeeper_overload_*``) and the structured log stream.
"""

from gatekeeper_tpu.resilience.faults import (  # noqa: F401
    FaultError,
    FaultPlan,
    FaultSpec,
    fault_point,
    inject,
    install,
    load_chaos_spec,
    set_metrics_registry,
    uninstall,
)
from gatekeeper_tpu.resilience.overload import (  # noqa: F401
    AdaptiveLimiter,
    DrainCoordinator,
    OverloadConfig,
    OverloadController,
    Shed,
)
from gatekeeper_tpu.resilience.policy import (  # noqa: F401
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)
