"""Resilience layer: fault-injection seam + deadline/retry/breaker policies.

``faults`` is the deterministic chaos seam (contextvar-scoped injection
points threaded through the webhook, external-data, apiserver, pipeline
and device-dispatch paths); ``policy`` is the unified failure-handling
layer (deadline budgets, jittered exponential retry, per-dependency
circuit breakers, graceful-degradation hooks).  Every injection, retry,
breaker transition and deadline miss flows into the metrics registry
(``gatekeeper_resilience_*``) and the structured log stream.
"""

from gatekeeper_tpu.resilience.faults import (  # noqa: F401
    FaultError,
    FaultPlan,
    FaultSpec,
    fault_point,
    inject,
    install,
    load_chaos_spec,
    set_metrics_registry,
    uninstall,
)
from gatekeeper_tpu.resilience.policy import (  # noqa: F401
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)
