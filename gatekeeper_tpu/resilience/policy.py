"""Unified resilience policies: deadline budgets, jittered retry,
circuit breakers, graceful-degradation hooks.

This replaces the stack's ad-hoc failure handling with one vocabulary
(the reference's failure semantics are explicit — webhook failurePolicy
fail-open/fail-closed, external-data failure policies with TTL-cache
fallback, watch 410 resync — so the failure *machinery* should be too):

- :class:`Deadline` — a wall-clock budget created per admission request
  and propagated by contextvar (:func:`deadline_scope` /
  :func:`current_deadline`), so every dependency call downstream of the
  webhook bounds its own waits by the request's remaining time.
- :class:`RetryPolicy` — seeded-jitter exponential backoff with a
  deadline cap; retries count into
  ``gatekeeper_resilience_retry_count{dependency}``.
- :class:`CircuitBreaker` — closed → open on a failure run, open →
  half-open after the reset timeout (bounded probes), half-open →
  closed on probe success / back to open on probe failure.  Transitions
  count into
  ``gatekeeper_resilience_breaker_transition_count{dependency,from,to}``
  and the current state is the
  ``gatekeeper_resilience_breaker_state{dependency}`` gauge
  (0 closed, 1 half-open, 2 open).

Everything takes an injectable ``clock`` so tests drive state machines
without real sleeps, and a ``seed`` so jitter sequences replay.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, Sequence


class DeadlineExceeded(Exception):
    """A deadline budget ran out (the webhook maps this onto its
    failurePolicy; dependencies surface it like any other failure)."""


class BreakerOpen(Exception):
    """A circuit breaker refused the call (dependency presumed down);
    callers degrade — stale cache, fallback lane, partial result."""

    def __init__(self, dependency: str, retry_after_s: float = 0.0):
        super().__init__(
            f"circuit breaker open for {dependency!r}"
            + (f" (retry in {retry_after_s:.1f}s)" if retry_after_s else ""))
        self.dependency = dependency
        self.retry_after_s = retry_after_s


# --- deadline budgets ----------------------------------------------------

class Deadline:
    """Wall-clock budget.  ``Deadline(0)`` (or None budget) is unlimited —
    every wait-bounding helper treats it as 'no deadline'."""

    def __init__(self, budget_s: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.budget_s = budget_s if budget_s and budget_s > 0 else None
        self._t0 = clock()

    def remaining(self) -> Optional[float]:
        """Seconds left (may be <= 0), or None when unlimited."""
        if self.budget_s is None:
            return None
        return self.budget_s - (self._clock() - self._t0)

    @property
    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def check(self, what: str = "") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"deadline budget {self.budget_s:.3f}s exhausted"
                + (f" in {what}" if what else ""))

    def bound(self, timeout_s: Optional[float]) -> Optional[float]:
        """Clamp a caller's timeout by the remaining budget (None in,
        None budget -> None out)."""
        r = self.remaining()
        if r is None:
            return timeout_s
        r = max(0.0, r)
        return r if timeout_s is None else min(timeout_s, r)


_ctx_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "gatekeeper_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    return _ctx_deadline.get()


@contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    """Propagate a request's budget to same-thread dependency calls."""
    token = _ctx_deadline.set(deadline)
    try:
        yield deadline
    finally:
        _ctx_deadline.reset(token)


# --- jittered exponential retry ------------------------------------------

class RetryPolicy:
    """Seeded full-jitter exponential backoff.

    ``backoff(attempt)`` for attempt k in [0, attempts-2] is
    ``uniform(base * mult^k * (1-jitter), base * mult^k)`` capped at
    ``cap_s`` — deterministic for a given seed (chaos runs replay)."""

    def __init__(self, attempts: int = 3, base_s: float = 0.05,
                 cap_s: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep,
                 metrics=None, dependency: str = ""):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        self.attempts = attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.dependency = dependency
        self.metrics = metrics
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def backoff(self, attempt: int) -> float:
        hi = min(self.cap_s, self.base_s * (self.multiplier ** attempt))
        lo = hi * (1.0 - self.jitter)
        with self._lock:
            return self._rng.uniform(lo, hi)

    def call(self, fn: Callable, *args,
             retry_on: Sequence[type] = (Exception,),
             giveup: Optional[Callable[[BaseException], bool]] = None,
             deadline: Optional[Deadline] = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             **kwargs):
        """Run ``fn`` with up to ``attempts`` tries.  ``giveup(exc)`` True
        means the failure is not transient (4xx, validation) — re-raise
        immediately.  A deadline (explicit or ambient via
        :func:`current_deadline`) bounds the whole loop: no retry sleep
        ever outlives the request budget."""
        if deadline is None:
            deadline = current_deadline()
        last: Optional[BaseException] = None
        for attempt in range(self.attempts):
            if deadline is not None and deadline.expired:
                try:
                    from gatekeeper_tpu.observability import tracing

                    tracing.add_event(
                        "deadline_exceeded",
                        dependency=self.dependency or "unknown",
                        attempt=attempt)
                except Exception:
                    pass
                raise DeadlineExceeded(
                    f"retry budget for {self.dependency or 'call'} "
                    "outlived the deadline") from last
            try:
                return fn(*args, **kwargs)
            except tuple(retry_on) as e:  # noqa: PERF203
                last = e
                if giveup is not None and giveup(e):
                    raise
                if attempt == self.attempts - 1:
                    raise
                delay = self.backoff(attempt)
                if deadline is not None:
                    r = deadline.remaining()
                    if r is not None:
                        if r <= 0:
                            raise
                        delay = min(delay, r)
                self._count_retry(attempt, e)
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(delay)
        raise last  # unreachable (loop always returns or raises)

    def _count_retry(self, attempt: int, exc: BaseException) -> None:
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.RESILIENCE_RETRIES,
                {"dependency": self.dependency or "unknown"})
        try:
            from gatekeeper_tpu.observability import tracing

            tracing.add_event("retry",
                              dependency=self.dependency or "unknown",
                              attempt=attempt + 1, error=str(exc))
        except Exception:
            pass
        try:
            from gatekeeper_tpu.utils.logging import log_event

            log_event("warning", "retrying after transient failure",
                      event_type="resilience_retry",
                      dependency=self.dependency, attempt=attempt + 1,
                      error=str(exc))
        except Exception:
            pass


# --- circuit breaker ------------------------------------------------------

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-dependency breaker with half-open probing.

    - CLOSED: calls flow; ``failure_threshold`` consecutive failures trip
      to OPEN.
    - OPEN: ``allow()`` is False until ``reset_timeout_s`` elapses, then
      the breaker moves to HALF_OPEN.
    - HALF_OPEN: at most ``half_open_max`` concurrent probes; a probe
      success closes the breaker, a probe failure re-opens it (fresh
      reset timer).
    """

    def __init__(self, dependency: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        self.dependency = dependency
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = max(1, half_open_max)
        self.metrics = metrics
        self.on_transition = on_transition
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        self._set_gauge()

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now?  In HALF_OPEN this *claims* a
        probe slot; callers must report the outcome via
        record_success/record_failure."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self.reset_timeout_s
                       - (self._clock() - self._opened_at))

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state in (HALF_OPEN, OPEN):
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._transition(OPEN)

    def call(self, fn: Callable, *args, **kwargs):
        """Guarded call: raises :class:`BreakerOpen` without touching the
        dependency when the breaker refuses."""
        if not self.allow():
            raise BreakerOpen(self.dependency, self.retry_after_s())
        try:
            out = fn(*args, **kwargs)
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return out

    # --- internals (call under self._lock) -------------------------------
    def _maybe_half_open(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.reset_timeout_s:
            self._transition(HALF_OPEN)

    def _transition(self, new: str) -> None:
        old, self._state = self._state, new
        if new == OPEN:
            self._opened_at = self._clock()
        if new in (OPEN, CLOSED):
            self._probes = 0
        if new == CLOSED:
            self._failures = 0
        self._set_gauge()
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.RESILIENCE_BREAKER_TRANSITIONS,
                {"dependency": self.dependency, "from": old, "to": new})
        try:
            from gatekeeper_tpu.observability import tracing

            tracing.add_event("breaker_transition",
                              dependency=self.dependency,
                              breaker_from=old, breaker_to=new)
        except Exception:
            pass
        try:
            from gatekeeper_tpu.utils.logging import log_event

            log_event("warning", "circuit breaker transition",
                      event_type="breaker_transition",
                      dependency=self.dependency,
                      breaker_from=old, breaker_to=new)
        except Exception:
            pass
        if self.on_transition is not None:
            self.on_transition(old, new)

    def _set_gauge(self) -> None:
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.set_gauge(M.RESILIENCE_BREAKER_STATE,
                                   _STATE_GAUGE[self._state],
                                   {"dependency": self.dependency})
