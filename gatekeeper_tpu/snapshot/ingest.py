"""Watch ingestion: the snapshot's event feed off the ObjectSource seam.

The reference's watch manager registers dynamic informers per GVK and
funnels their events into the cachemanager (pkg/watch/manager.go); here
the equivalent is :class:`WatchIngester` — one ``subscribe()`` per GVK on
any ObjectSource (``FakeCluster``, ``KubeCluster``), callbacks ENQUEUE
only (the source's watch threads never touch snapshot state), and the
audit thread applies the queue as row patches via
:meth:`ClusterSnapshot.pump`.

Replay semantics make this self-healing: both sources replay current
state as ADDED on subscribe, and ``KubeCluster`` re-replays after a 410
Gone relist — the snapshot's no-op-patch detection (resourceVersion /
deep equality) absorbs the churn without dirtying clean rows.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional, Sequence

from gatekeeper_tpu.utils.unstructured import gvk_of


class WatchIngester:
    """Fan-in of per-GVK watch subscriptions into a ClusterSnapshot.

    ``from_rvs`` (``{gvk: resourceVersion}``, the snapshot spill's rv
    high-water marks) makes a restart cold-start-free on the watch side
    too: sources that support it (``KubeCluster``) resubscribe straight
    FROM the recorded rv — no initial list, missed events replay off the
    server's watch cache — seeding the vanished-object diff with the
    spilled keys so a 410-forced relist still synthesizes DELETEDs.
    Sources without rv resume fall back to a full replay, which the
    snapshot's no-op-patch detection absorbs.

    ``rvs`` tracks the newest resourceVersion seen per GVK (event
    objects advance it) — the value the next spill records."""

    def __init__(self, snapshot, source, gvks: Sequence[tuple],
                 on_error: Optional[Callable[[Exception], None]] = None,
                 from_rvs: Optional[dict] = None, cluster: str = ""):
        self.snapshot = snapshot
        self.source = source
        self.gvks = list(gvks)
        self.on_error = on_error
        self.from_rvs = dict(from_rvs or {})
        # fleet mode: which cluster this ingester feeds — the id the
        # FleetEvaluator labels its metrics/log lines with, so N
        # ingesters' errors and rv marks stay attributable
        self.cluster = cluster
        # gvk -> newest seen resourceVersion; starts at the resume marks
        # so a quiet restart's next spill keeps the spilled rvs
        self.rvs: dict = dict(self.from_rvs)
        self._cancels: list = []
        self._lock = threading.Lock()
        self.events_seen = 0

    def _on_event(self, ev) -> None:
        self.events_seen += 1
        rv = ((ev.obj.get("metadata") or {})
              .get("resourceVersion", "")) or ""
        if rv:
            self.rvs[gvk_of(ev.obj)] = rv
        self.snapshot.enqueue(ev.type, ev.obj)

    def _subscribe(self, gvk: tuple):
        rv = self.from_rvs.get(gvk, "")
        # a warm-loaded snapshot always seeds the vanished-object diff
        # (spilled keys the source no longer holds must synthesize
        # DELETED) even when the source records no rv marks — only the
        # list-skip needs a real rv to resume from
        if rv or getattr(self.snapshot, "warm_loaded", False):
            try:
                return self.source.subscribe(
                    gvk, self._on_event, replay=True, from_rv=rv,
                    seed_known=self.snapshot.keys_for_gvk(gvk))
            except TypeError:
                pass  # source without warm resume: full replay below
        return self.source.subscribe(gvk, self._on_event, replay=True)

    def start(self) -> "WatchIngester":
        with self._lock:
            for gvk in self.gvks:
                try:
                    self._cancels.append(self._subscribe(gvk))
                except Exception as e:  # noqa: PERF203
                    if self.on_error is not None:
                        self.on_error(e)
                    else:
                        raise
        return self

    def stop(self) -> None:
        with self._lock:
            cancels, self._cancels = self._cancels, []
        for cancel in cancels:
            try:
                cancel()
            except Exception:
                pass

    def pump(self, max_events: Optional[int] = None) -> int:
        """Apply queued events to the snapshot (audit-thread side)."""
        return self.snapshot.pump(max_events=max_events)


def gvks_of(objects: Iterable[dict]) -> list:
    """Distinct GVKs of an object iterable (FakeCluster-style sources
    without discovery), insertion-ordered."""
    from gatekeeper_tpu.utils.unstructured import gvk_of

    seen: dict = {}
    for obj in objects:
        seen.setdefault(gvk_of(obj), None)
    return list(seen)
