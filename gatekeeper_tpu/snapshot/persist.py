"""Snapshot spill: cold-start-free restarts for the audit data plane.

PR 6 cut the steady-state sweep to O(churn) and PR 12's compile cache
cut the restart COMPILE cost to zero — but a restarted auditor still
relists + reflattens the world before its first sweep (SNAPSHOT_BENCH:
3.42s for 20k objects, and that is the cheap part of a real cluster).
This module spills the complete resident audit state to disk and loads
it back on boot:

- per-group tall ColumnBatches, trimmed to real extents and re-padded to
  capacity on load (``GroupStore.export_rows``/``import_rows``);
- the interned vocab string table (sid arrays point into it — the
  current vocab must be a PREFIX of the spilled one, exactly the
  CompileCache replay rule, so template-boot interning composes);
- the RowIdMap with its high-water mark (monotone ids survive restart,
  so gid-keyed verdicts and phase-2 interning stay valid and a
  post-restart create can never collide with a retired id);
- tombstone/dirty sets and the per-(constraint, row) VerdictStore
  (loaded rows are CLEAN with their persisted verdicts — the first tick
  re-evaluates nothing);
- the per-GVK resourceVersion high-water mark, so the watch ingester
  resubscribes FROM the spill's rv instead of list+replaying; a server
  that compacted past it answers 410 and the PR 6 ``watch_iter`` seam's
  relist + synthetic-DELETE fallback doubles as stale-spill recovery;
- (optional) the external-data ProviderColumns with per-key remaining
  TTL, so a warm restart re-fetches only what actually expired.

Integrity mirrors :class:`~gatekeeper_tpu.drivers.generation.
CompileCache`: content sha256 per section, format / flatten-schema /
jax-version fields plus the constraint-set and template-set digests in
the header, per-group schema digests validated against the freshly
derived plan.  A corrupt or drifted spill is DELETED and the boot falls
back to a clean relist — it is never served.  Writes are atomic
(tmp + rename, header last) so a crashed writer leaves no torn spill.

:class:`SnapshotSpiller` runs the pickling + write on a daemon worker:
the audit thread only pays the under-lock array capture (memcpy), so
steady-state ticks are untouched.  Spills happen after each clean
resync and at drain.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import pickle
import threading
import time
import zlib
from typing import Optional, Sequence

from gatekeeper_tpu.ops.flatten import FLATTEN_SCHEMA_VERSION

# bump when the on-disk spill layout changes
SPILL_FORMAT = 1

# --snapshot-spill-compress: section codecs.  'none' is byte-identical
# to the pre-codec format (header included — the codec key is only
# written when it isn't the default), the right trade on 1-core hosts
# where zlib CPU costs more than the bytes; 'zlib' compresses each
# section on the spill worker (pickled column arrays compress ~3-5x),
# the right trade on NVMe-rich many-core hosts.  The section sha256
# guards the STORED bytes, so integrity checking is codec-agnostic and
# the loader auto-detects from the header — flipping the flag never
# strands an existing spill.
SPILL_CODECS = ("none", "zlib")

HEADER = "snapshot.json"

# miss reasons for gatekeeper_snapshot_spill_load_miss_count{reason}
MISS_COLD = "cold"          # no spill on disk
MISS_CORRUPT = "corrupt"    # unreadable header / section sha / pickle fail
MISS_VERSION = "version"    # format / flatten-schema / jax drift
MISS_PLAN = "plan"          # constraint- or template-set digest drift
MISS_VOCAB = "vocab"        # spilled vocab not replayable here
MISS_SCHEMA = "schema"      # a group's schema digest drifted
MISS_CLUSTER = "cluster"    # header's cluster id != this spill's owner


def templates_digest(client) -> str:
    """Template-set digest of a client's loaded templates — the header
    guard against template drift that leaves the constraint spec AND the
    lowered schemas unchanged (e.g. a message-text edit) but would make
    persisted verdicts stale."""
    from gatekeeper_tpu.drivers.generation import (template_digest,
                                                   template_set_digest)

    try:
        return template_set_digest(
            template_digest(t) for t in client.templates())
    except Exception:
        return ""


def _gvk_key(gvk: tuple) -> str:
    return "|".join(gvk)


def _gvk_unkey(s: str) -> tuple:
    return tuple(s.split("|", 2))


class SnapshotSpill:
    """One spill directory: versioned header + sha256-guarded sections.

    Layout::

        DIR/snapshot.json       header (format/version fields, digests,
                                per-section sha256+bytes, rv marks)
        DIR/snapshot.rows.pkl   groups + RowIdMap + verdicts + dirty set
        DIR/snapshot.vocab.pkl  the interned string table
        DIR/snapshot.aux.pkl    optional: extdata columns, generated
                                verdicts

    The header is written LAST (tmp + rename), so its presence commits
    the spill; a load that finds any section torn, truncated or
    tampered deletes the whole spill and reports a miss.

    ``cluster_id`` (fleet mode — one spill subdir per cluster under a
    shared ``--snapshot-spill`` root): the id is written into the
    header and checked on load.  A mismatch (a cluster pointed at a
    sibling's spill dir) is a counted ``cluster`` miss and a clean
    relist — the spill itself is NOT deleted, it still belongs to its
    real owner.
    """

    def __init__(self, root: str, metrics=None, compress: str = "none",
                 cluster_id: str = "", delta: bool = False,
                 full_every: int = 8):
        if compress not in SPILL_CODECS:
            raise ValueError(
                f"unknown spill codec {compress!r} (want one of "
                f"{SPILL_CODECS})")
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.metrics = metrics
        self.compress = compress
        self.cluster_id = cluster_id
        # incremental spills (--snapshot-spill-delta): groups split into
        # per-group section files and a spill rewrites ONLY the groups
        # whose mutation mark moved since the last successful write —
        # O(churn) disk instead of O(cluster).  Every ``full_every``-th
        # spill (and the first, and any after a failure or delete) is a
        # full rewrite that also prunes orphaned group files — the
        # periodic compaction path.  delta=False keeps the inline
        # single-section format byte-identical to PR 13/14.
        self.delta = bool(delta)
        self.full_every = max(1, int(full_every))
        self._dlock = threading.Lock()
        self._last_marks: dict = {}     # kinds-key -> mutations written
        self._last_sections: dict = {}  # group file -> {"sha256","bytes"}
        self._spills_since_full = 0
        self._force_full = True
        self.load_hits = 0
        self.load_misses = 0
        self.miss_reasons: dict = {}
        self.spill_count = 0
        self.last_spill_s = 0.0
        self.last_spill_bytes = 0
        self.delta_spills = 0       # spills that reused >= 1 group file
        self.groups_skipped = 0     # group sections reused across spills

    # --- paths / accounting -------------------------------------------
    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def _sections(self) -> tuple:
        return ("snapshot.rows.pkl", "snapshot.vocab.pkl",
                "snapshot.aux.pkl")

    def _count(self, hit: bool, reason: str = "") -> None:
        if hit:
            self.load_hits += 1
        else:
            self.load_misses += 1
            self.miss_reasons[reason] = \
                self.miss_reasons.get(reason, 0) + 1
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            if hit:
                self.metrics.inc_counter(M.SNAPSHOT_SPILL_LOAD_HITS)
            else:
                self.metrics.inc_counter(M.SNAPSHOT_SPILL_LOAD_MISS,
                                         {"reason": reason})

    def _reject(self, reason: str) -> None:
        """A corrupt/drifted spill is deleted so the next clean spill
        replaces it — it must never be half-served."""
        self._count(False, reason)
        self.delete()

    @staticmethod
    def _group_file(kinds) -> str:
        """Stable per-group section filename: the kinds-set IS the
        group identity, so a group keeps one file across spills and a
        delta rewrite replaces it in place (atomic ``os.replace``)."""
        key = "|".join(kinds)
        return ("snapshot.group-"
                + hashlib.sha256(key.encode()).hexdigest()[:12] + ".pkl")

    def delete(self) -> None:
        for name in (HEADER,) + self._sections():
            try:
                os.remove(self._path(name))
            except OSError:
                pass
        for p in glob.glob(self._path("snapshot.group-*.pkl")):
            try:
                os.remove(p)
            except OSError:
                pass
        # the next delta spill has nothing on disk to reuse
        with self._dlock:
            self._last_marks.clear()
            self._last_sections.clear()
            self._force_full = True

    @staticmethod
    def _versions() -> tuple:
        import jax

        try:
            import jaxlib

            jl = getattr(jaxlib, "__version__", "?")
        except Exception:
            jl = "?"
        return jax.__version__, jl

    # --- capture (audit thread, under the snapshot lock) ---------------
    def capture(self, snapshot, rvs: Optional[dict] = None,
                extdata_lane=None, aux: Optional[dict] = None,
                templates: str = "") -> dict:
        """Assemble the spill state.  Array copies happen inside
        ``snapshot.export_state`` under its lock; everything here is
        cheap bookkeeping — pickling is :meth:`write`'s job.

        Delta mode: groups whose mutation mark still equals what the
        last SUCCESSFUL write put on disk export a skipped stub and pay
        zero array copies here too.  Marks only advance after a write
        commits, so a stub can never reference bytes that aren't
        durable."""
        if self.delta:
            known = None
            with self._dlock:
                if (not self._force_full and self._last_marks
                        and self._spills_since_full + 1 < self.full_every):
                    known = dict(self._last_marks)
            state = snapshot.export_state(known_marks=known)
        else:
            # no kwarg off the delta path: snapshot doubles (and older
            # exporters) need not know about delta marks
            state = snapshot.export_state()
        vocab = snapshot.evaluator.driver.vocab
        ext = None
        if extdata_lane is not None:
            try:
                ext = extdata_lane.export_columns()
            except Exception:
                ext = None
        return {
            "state": state,
            "vocab": list(vocab._to_str),
            "rvs": dict(rvs or {}),
            "aux": dict(aux or {}),
            "extdata": ext,
            "templates": templates,
        }

    # --- write (off-thread safe: no snapshot state touched) -------------
    def write(self, captured: dict) -> dict:
        """Pickle + sha + atomic write.  Returns spill stats; failures
        are swallowed into the stats (a failed spill must never take the
        audit plane down — the previous spill, if any, stays intact
        because every replace is atomic and the header goes last)."""
        from gatekeeper_tpu.observability import tracing

        t0 = time.perf_counter()
        state = captured["state"]
        with tracing.span("snapshot.spill", rows=state.get("rows", 0)):
            try:
                jv, jlv = self._versions()
                manifest: list = []
                group_payloads: dict = {}
                reused: dict = {}
                any_skipped = False
                rows_state = state
                if self.delta:
                    manifest, group_payloads, reused, any_skipped, err = \
                        self._split_groups(state)
                    if err is not None:
                        return err
                    rows_state = {k: v for k, v in state.items()
                                  if k != "groups"}
                    rows_state["group_files"] = manifest
                payloads = {
                    "snapshot.rows.pkl": pickle.dumps(rows_state),
                    "snapshot.vocab.pkl": pickle.dumps(captured["vocab"]),
                    "snapshot.aux.pkl": pickle.dumps(
                        {"aux": captured.get("aux") or {},
                         "extdata": captured.get("extdata")}),
                    **{name: pickle.dumps(gp)
                       for name, gp in group_payloads.items()},
                }
                if self.compress == "zlib":
                    payloads = {name: zlib.compress(raw)
                                for name, raw in payloads.items()}
                header = {
                    "format": SPILL_FORMAT,
                    "flatten_schema_version": FLATTEN_SCHEMA_VERSION,
                    "jax": jv, "jaxlib": jlv,
                    # codec key only when non-default, so 'none' spills
                    # stay byte-identical to the pre-codec format
                    **({"codec": self.compress}
                       if self.compress != "none" else {}),
                    # cluster ownership (fleet mode); absent for the
                    # single-cluster shape, keeping it byte-identical
                    **({"cluster": self.cluster_id}
                       if self.cluster_id else {}),
                    "templates": captured.get("templates", ""),
                    "rows": state.get("rows", 0),
                    "rv": {_gvk_key(g): rv
                           for g, rv in captured["rvs"].items()},
                    # skipped groups' on-disk sections are reused
                    # verbatim: their recorded sha/bytes re-enter the
                    # header so the loader validates every section the
                    # same way, fresh or reused
                    "sections": {
                        **{name: {"sha256":
                                  hashlib.sha256(raw).hexdigest(),
                                  "bytes": len(raw)}
                           for name, raw in payloads.items()},
                        **reused},
                    "saved_at": time.time(),
                }
                for name, raw in payloads.items():
                    tmp = self._path(name) + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(raw)
                    os.replace(tmp, self._path(name))
                tmp = self._path(HEADER) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(header, f)
                os.replace(tmp, self._path(HEADER))
                if self.delta:
                    self._delta_commit(manifest, header["sections"],
                                       reused, any_skipped)
            except Exception as e:
                if self.delta:
                    # on-disk group files may be torn relative to the
                    # recorded marks: rebuild everything next spill
                    with self._dlock:
                        self._force_full = True
                return {"ok": False, "error": str(e)}
        dt = time.perf_counter() - t0
        nbytes = sum(len(raw) for raw in payloads.values())
        self.spill_count += 1
        self.last_spill_s = dt
        self.last_spill_bytes = nbytes
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.set_gauge(M.SNAPSHOT_SPILL_SECONDS, dt)
            self.metrics.set_gauge(M.SNAPSHOT_SPILL_BYTES, nbytes)
        return {"ok": True, "seconds": dt, "bytes": nbytes,
                "rows": state.get("rows", 0)}

    def _split_groups(self, state: dict):
        """Delta mode: map each exported group to its own section file.
        Returns ``(manifest, payloads, reused, any_skipped, err)`` —
        ``payloads`` holds groups captured fresh this round, ``reused``
        the recorded header metadata for skipped stubs whose on-disk
        section carries over unchanged."""
        manifest: list = []
        payloads: dict = {}
        reused: dict = {}
        any_skipped = False
        for gp in state.get("groups") or []:
            kinds = list(gp["kinds"])
            fname = self._group_file(kinds)
            manifest.append({"file": fname, "kinds": kinds,
                             "mutations": int(gp.get("mutations", 0))})
            if gp.get("skipped"):
                any_skipped = True
                with self._dlock:
                    meta = self._last_sections.get(fname)
                if meta is None \
                        or not os.path.exists(self._path(fname)):
                    # the stub references a section this dir does not
                    # hold (failed/raced write, external delete): fail
                    # closed, force the next spill full
                    with self._dlock:
                        self._force_full = True
                    return None, None, None, False, {
                        "ok": False,
                        "error": f"delta stub without section {fname}"}
                reused[fname] = dict(meta)
            else:
                payloads[fname] = gp
        return manifest, payloads, reused, any_skipped, None

    def _delta_commit(self, manifest, sections_meta, reused,
                      any_skipped) -> None:
        """Post-write bookkeeping for a committed delta-mode spill.
        Marks and section metadata advance ONLY here, so a later
        capture's stub can never outrun what is durably on disk.  A
        spill that rewrote every group (the periodic full, or a fully
        dirty delta) doubles as compaction: group files no longer in
        the manifest are orphans of deleted groups and get pruned."""
        group_meta = {m["file"]: sections_meta[m["file"]]
                      for m in manifest}
        full = not any_skipped
        with self._dlock:
            self._last_marks = {"|".join(m["kinds"]): m["mutations"]
                                for m in manifest}
            self._last_sections = group_meta
            self._force_full = False
            self._spills_since_full = \
                0 if full else self._spills_since_full + 1
        if any_skipped:
            self.delta_spills += 1
            self.groups_skipped += len(reused)
        if full:
            keep = set(group_meta)
            for p in glob.glob(self._path("snapshot.group-*.pkl")):
                if os.path.basename(p) not in keep:
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    def save(self, snapshot, rvs: Optional[dict] = None,
             extdata_lane=None, aux: Optional[dict] = None,
             templates: str = "") -> dict:
        """Synchronous capture + write (benches, tests, drain flush)."""
        return self.write(self.capture(snapshot, rvs=rvs,
                                       extdata_lane=extdata_lane,
                                       aux=aux, templates=templates))

    # --- load -----------------------------------------------------------
    def load(self, snapshot, constraints: Sequence,
             extdata_lane=None, templates: str = "") -> Optional[dict]:
        """Validate + adopt a spill into ``snapshot``.

        Returns ``{"rows", "rvs", "aux"}`` on a hit (the snapshot is now
        warm: ``stale`` False, rows clean, verdicts resident), or None
        on any miss — reason counted in
        ``gatekeeper_snapshot_spill_load_miss_count{reason}`` and, for
        corrupt/drifted spills, the files deleted.  The caller falls
        back to the normal relist boot; nothing about the snapshot
        changed on a miss."""
        from gatekeeper_tpu.observability import tracing

        with tracing.span("snapshot.load") as sp:
            out = self._load_impl(snapshot, constraints, extdata_lane,
                                  templates)
            sp.set_attribute("hit", out is not None)
            if out is not None:
                sp.set_attribute("rows", out["rows"])
            return out

    def _load_impl(self, snapshot, constraints, extdata_lane,
                   templates) -> Optional[dict]:
        header_p = self._path(HEADER)
        if not os.path.exists(header_p):
            self._count(False, MISS_COLD)
            return None
        try:
            with open(header_p) as f:
                header = json.load(f)
        except Exception:
            self._reject(MISS_CORRUPT)
            return None
        jv, jlv = self._versions()
        if (header.get("format") != SPILL_FORMAT
                or header.get("flatten_schema_version")
                != FLATTEN_SCHEMA_VERSION
                or header.get("jax") != jv
                or header.get("jaxlib") != jlv):
            self._reject(MISS_VERSION)
            return None
        if self.cluster_id and \
                header.get("cluster", "") != self.cluster_id:
            # another cluster's spill (misrouted --snapshot-spill dir):
            # counted miss + clean relist, but NEVER deleted — the data
            # still belongs to its real owner
            self._count(False, MISS_CLUSTER)
            return None
        if header.get("templates", "") != templates:
            self._reject(MISS_PLAN)
            return None
        # codec auto-detect: absent = the pre-codec 'none' format; an
        # unknown codec is a format drift (a newer writer), not corruption
        codec = header.get("codec", "none")
        if codec not in SPILL_CODECS:
            self._reject(MISS_VERSION)
            return None
        sections: dict = {}
        for name, meta in (header.get("sections") or {}).items():
            try:
                with open(self._path(name), "rb") as f:
                    raw = f.read()
            except OSError:
                self._reject(MISS_CORRUPT)
                return None
            if hashlib.sha256(raw).hexdigest() != meta.get("sha256"):
                self._reject(MISS_CORRUPT)
                return None
            if codec == "zlib":
                try:
                    raw = zlib.decompress(raw)
                except zlib.error:
                    self._reject(MISS_CORRUPT)
                    return None
            try:
                sections[name] = pickle.loads(raw)
            except Exception:
                self._reject(MISS_CORRUPT)
                return None
        state = sections.get("snapshot.rows.pkl")
        vocab_snap = sections.get("snapshot.vocab.pkl")
        auxpack = sections.get("snapshot.aux.pkl") or {}
        if state is None or vocab_snap is None:
            self._reject(MISS_CORRUPT)
            return None
        if "group_files" in state:
            # delta layout: rows.pkl carries a manifest; the group
            # payloads live in their own (already sha-validated)
            # sections.  Reassemble the classic state shape so
            # adopt_spill is layout-agnostic.
            try:
                state = dict(state)
                state["groups"] = [sections[gf["file"]]
                                   for gf in state["group_files"]]
            except (KeyError, TypeError):
                self._reject(MISS_CORRUPT)
                return None
        # constraint-set currency: the spilled digest must equal the
        # digest of the LIVE constraint set (spec + lowered kinds) — a
        # changed set means the verdicts/grouping no longer apply
        if state.get("digest") != snapshot._cons_digest(constraints):
            self._reject(MISS_PLAN)
            return None
        # vocab replay (the CompileCache rule, extended one direction
        # for fleet mode): the spill's snapshot and the current table
        # must be prefix-compatible.  Current ⊆ snapshot replays the
        # tail in recorded order (the restart shape); snapshot ⊆
        # current is ALSO a hit with nothing to replay — a sibling
        # cluster's earlier load (or its template boot) already grew
        # the shared append-only vocab past this spill's snapshot, and
        # every resident sid still points at the same string.  Loading
        # a fleet is therefore N spills against one shared replay.
        vocab = snapshot.evaluator.driver.vocab
        cur = vocab._to_str
        if len(cur) <= len(vocab_snap):
            if vocab_snap[: len(cur)] != cur:
                self._count(False, MISS_VOCAB)  # spill itself is fine
                return None
            for s in vocab_snap[len(cur):]:
                vocab.intern(s)
        elif cur[: len(vocab_snap)] != vocab_snap:
            self._count(False, MISS_VOCAB)
            return None
        try:
            rows = snapshot.adopt_spill(constraints, state)
        except ValueError:
            self._reject(MISS_SCHEMA)
            return None
        if extdata_lane is not None and auxpack.get("extdata"):
            try:
                # downtime consumes the spilled keys' remaining TTL:
                # what expired while the process was down drops here
                elapsed = max(0.0, time.time()
                              - float(header.get("saved_at", 0.0)))
                extdata_lane.import_columns(auxpack["extdata"],
                                            elapsed_s=elapsed)
            except Exception:
                pass  # extdata re-fetches through the bulk path
        self._count(True)
        return {
            "rows": rows,
            "rvs": {_gvk_unkey(k): rv
                    for k, rv in (header.get("rv") or {}).items()},
            "aux": auxpack.get("aux") or {},
        }

    def stats(self) -> dict:
        return {"load_hits": self.load_hits,
                "load_misses": self.load_misses,
                "miss_reasons": dict(self.miss_reasons),
                "spills": self.spill_count,
                "last_spill_s": self.last_spill_s,
                "last_spill_bytes": self.last_spill_bytes,
                "delta_spills": self.delta_spills,
                "groups_skipped": self.groups_skipped}


class SnapshotSpiller:
    """Off-audit-thread spill writer.

    ``spill()`` captures the state under the snapshot lock (array
    copies only) and enqueues it; a daemon worker pickles + writes.
    Coalescing: a request arriving while one is queued replaces it (the
    newest capture wins — a capture is always a complete, loadable
    description of the state: even delta-mode stubs name the durable
    sections they reuse, and marks only advance after a write commits,
    so dropping the older capture loses nothing).  ``wait`` blocks for
    the write (drain flush, benches)."""

    def __init__(self, spill: SnapshotSpill, snapshot,
                 rvs_fn=None, extdata_lane=None, aux_fn=None,
                 templates_fn=None):
        self.spill = spill
        self.snapshot = snapshot
        self.rvs_fn = rvs_fn
        self.extdata_lane = extdata_lane
        self.aux_fn = aux_fn
        self.templates_fn = templates_fn
        self._cv = threading.Condition()
        self._pending: Optional[dict] = None
        self._busy = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self.last_result: Optional[dict] = None

    def _capture(self) -> dict:
        rvs = self.rvs_fn() if self.rvs_fn is not None else None
        aux = self.aux_fn() if self.aux_fn is not None else None
        templates = self.templates_fn() if self.templates_fn is not None \
            else ""
        return self.spill.capture(self.snapshot, rvs=rvs,
                                  extdata_lane=self.extdata_lane,
                                  aux=aux, templates=templates)

    def spill_now(self) -> dict:
        """Synchronous capture + write on the calling thread (drain)."""
        result = self.spill.write(self._capture())
        with self._cv:
            self.last_result = result
        return result

    def request(self, wait: bool = False) -> None:
        """Capture now (cheap, on the caller), write in the background.
        The first call lazily starts the worker."""
        captured = self._capture()
        with self._cv:
            if self._stopped:
                return
            self._pending = captured
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="snapshot-spill", daemon=True)
                self._thread.start()
            self._cv.notify_all()
            if wait:
                while self._pending is not None or self._busy:
                    self._cv.wait(0.05)

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._stopped:
                    self._cv.wait(0.5)
                if self._pending is None and self._stopped:
                    return
                captured, self._pending = self._pending, None
                self._busy = True
            try:
                result = self.spill.write(captured)
            except Exception as e:  # never take the process down
                result = {"ok": False, "error": str(e)}
            with self._cv:
                self.last_result = result
                self._busy = False
                self._cv.notify_all()
                if self._pending is None and self._stopped:
                    return

    def stop(self, flush: bool = True) -> None:
        """Stop the worker; with ``flush`` (the drain path) a final
        spill writes synchronously first, so a clean SIGTERM never loses
        the resident state it just paid to build."""
        if flush:
            try:
                self.spill_now()
            except Exception:
                pass
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
