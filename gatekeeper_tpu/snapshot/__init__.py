"""Watch-driven incremental audit: the resident columnar cluster
snapshot (see :mod:`gatekeeper_tpu.snapshot.store` for the design)."""

from gatekeeper_tpu.snapshot.device_residency import (  # noqa: F401
    DeviceResidency,
    ResidentGroup,
)
from gatekeeper_tpu.snapshot.ingest import WatchIngester, gvks_of  # noqa: F401
from gatekeeper_tpu.snapshot.persist import (  # noqa: F401
    SnapshotSpill,
    SnapshotSpiller,
    templates_digest,
)
from gatekeeper_tpu.snapshot.store import (  # noqa: F401
    ClusterSnapshot,
    GroupStore,
    SnapshotConfig,
    VerdictStore,
    concat_group_rows,
    obj_key,
    row_signature,
)
