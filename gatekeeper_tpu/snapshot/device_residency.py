"""Device-resident snapshot columns: HBM as the cluster cache.

The snapshot store (snapshot/store.py) already keeps per-group tall
ColumnBatches resident HOST-side and ticks O(churn) — but every sweep
chunk still pays slice_rows (host gather) + pack_transfer_cols (host
pack) + device_put (H2D wire) for rows that have not changed since the
last tick (SWEEP1M: 119MB H2D per 1M-object sweep, all of it re-upload
of clean rows).  This module promotes residency one level:

- each routed :class:`GroupStore`'s tall batch lives ON DEVICE as the
  same dtype-packed transfer buffers a sweep dispatch would build
  (``pack_transfer_cols`` with ``stats=None`` — a schema-only layout
  that patch slivers reproduce exactly), uploaded once per layout
  generation;
- the per-(constraint, row) match masks live on device too (bool
  [C, cap]), with a host mirror the differential lane asserts against;
- watch patches apply as device ``scatter``: the dirty rows flatten
  into a sliver batch (the store's normal patch lane), pack under the
  SAME layout, and land with ``buf.at[rows].set(sliver)`` — H2D is
  O(churn), never O(cluster);
- an audit chunk over resident rows ships only a row-index gather
  vector (cached per chunk shape, so a warm full tick over unchanged
  membership uploads ZERO bytes) and the fused sweep gathers columns +
  masks on device (parallel/sharded.py ``_sweep_fn_resident*``).

Bit-identity to the host-column path holds by construction: the
gathered device rows are the same values ``slice_rows`` would gather,
pad slots gather row 0 but carry a False mask column (exactly the
False pad masks of a host chunk), and masks are computed per
(constraint, object) by the same ``constraint_masks`` the dispatch
path runs — per-object pure, so patch-time masks equal chunk-time
masks.  ``tests/test_device_residency.py`` pins verdict bit-identity
across clean, dirty-sliver and post-evict ticks.

Degradation: the built-in ``device_residency_evict`` action
(resilience/overload.py) demotes every resident group back to host
columns on an SLO breach — ``prepare`` polls it, frees the device
buffers, and falls back until the action releases (re-upload is lazy).
Hosts without an accelerator degrade the same way automatically
(mode "auto"), with the reason logged once — tier-1 stays green on the
1-core CPU host while mode "on" keeps the lane testable everywhere.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from gatekeeper_tpu.ir.program import pack_batch_cols, slim_cols
from gatekeeper_tpu.parallel.sharded import pack_transfer_cols

# residency modes (--snapshot-residency): 'auto' promotes only when an
# accelerator backs the mesh (CPU hosts keep host columns, logged once);
# 'on' forces promotion (the CPU differential/test shape); 'off'
# disables the lane entirely
RESIDENCY_MODES = ("auto", "on", "off")


def _layout_equal(a: tuple, b: tuple) -> bool:
    return a == b


class ResidentGroup:
    """Device mirror of one GroupStore: packed column buffers + masks.

    ``cols_dev`` maps dtype string -> device array [cap, W] (the
    pack_transfer_cols buffers); ``mask_dev`` is bool [C, cap] in
    constraint-grid order (sorted lowered kinds, then the group's
    constraint order per kind — the order every dispatch uses);
    ``mask_host`` is its host mirror, the differential reference."""

    __slots__ = ("store", "kinds", "by_kind", "uids", "cols_layout",
                 "cap", "c_total", "cols_dev", "mask_dev", "mask_host",
                 "mutation_mark", "layout_version", "idx_cache",
                 "resident_bytes", "needs")

    def __init__(self, store, kinds, by_kind, uids, needs):
        self.store = store
        self.kinds = kinds
        self.by_kind = by_kind
        self.uids = uids
        self.needs = needs
        self.cols_layout: tuple = ()
        self.cap = 0
        self.c_total = sum(len(by_kind[k]) for k in kinds)
        self.cols_dev: dict = {}
        self.mask_dev = None
        self.mask_host: Optional[np.ndarray] = None
        self.mutation_mark = -1
        self.layout_version = -1
        # tuple(positions) -> device int32 gather vector (pad slots -1);
        # a warm full tick's chunk boundaries are deterministic, so the
        # second pass hits every entry and uploads nothing
        self.idx_cache: dict = {}
        self.resident_bytes = 0

    def chunk_idx(self, positions, pad_n: int) -> tuple:
        """(idx_dev [pad_n] int32, uploaded_bytes) — cached per position
        tuple; -1 marks pad slots (their mask column is forced False on
        device, so what they gather never matters)."""
        import jax

        key = (tuple(positions), pad_n)
        hit = self.idx_cache.get(key)
        if hit is not None:
            return hit, 0
        idx = np.full(pad_n, -1, np.int32)
        idx[: len(positions)] = positions
        dev = jax.device_put(idx)
        if len(self.idx_cache) > 4096:
            self.idx_cache.clear()
        self.idx_cache[key] = dev
        return dev, idx.nbytes


class DeviceResidency:
    """Owner of the device-resident snapshot groups of ONE evaluator.

    ``prepare(store)`` is the single seam the audit/fleet sweeps call
    per group per tick: it syncs the device mirror (full upload on
    layout change, scatter-patch for dirty rows, nothing when clean)
    and returns the :class:`ResidentGroup`, or None when the lane is
    unavailable (no device, multi-chip mesh, extdata joins, eviction
    degradation active) — callers then take the host-column path
    unchanged."""

    def __init__(self, evaluator, metrics=None, mode: str = "auto",
                 cluster: str = ""):
        if mode not in RESIDENCY_MODES:
            raise ValueError(f"unknown residency mode {mode!r} "
                             f"(want one of {RESIDENCY_MODES})")
        self.evaluator = evaluator
        self.metrics = metrics
        self.mode = mode
        self.cluster = cluster
        self._lock = threading.RLock()
        self._groups: dict = {}  # id(store) -> ResidentGroup
        self._logged_reasons: set = set()
        self.h2d_bytes = 0       # bytes this residency actually uploaded
        self.upload_count = 0    # full group uploads
        self.patch_count = 0     # scatter-patch syncs
        self.evictions = 0
        self._evicted_by_slo = False

    # --- availability ----------------------------------------------------
    def _log_fallback(self, reason: str, **fields) -> None:
        if reason in self._logged_reasons:
            return
        self._logged_reasons.add(reason)
        from gatekeeper_tpu.utils.logging import log_event

        log_event("info", "snapshot residency falling back to host "
                  f"columns: {reason}",
                  event_type="residency_fallback", reason=reason,
                  **fields)

    def available(self) -> bool:
        """Whether the resident lane may serve at all right now."""
        if self.mode == "off":
            return False
        ev = self.evaluator
        if ev is None or ev.mesh.size != 1:
            self._log_fallback("multi-chip mesh (resident gather is "
                              "single-chip; see ROADMAP NEXT)")
            return False
        if self.mode == "auto":
            import jax

            try:
                backend = jax.default_backend()
            except Exception:
                backend = "cpu"
            if backend == "cpu":
                self._log_fallback("no accelerator (mode=auto on a CPU "
                                  "host)")
                return False
        from gatekeeper_tpu.resilience.overload import (
            DEVICE_RESIDENCY_EVICT, degradation_active)

        if degradation_active(DEVICE_RESIDENCY_EVICT, self.cluster):
            if not self._evicted_by_slo:
                self._evicted_by_slo = True
                self.evict_all("slo degradation "
                               "(device_residency_evict active)")
            return False
        self._evicted_by_slo = False
        return True

    # --- eviction --------------------------------------------------------
    def evict_all(self, reason: str = "") -> int:
        """Drop every device mirror (HBM freed as the arrays release);
        host columns keep serving and re-upload happens lazily on the
        next eligible ``prepare``.  Returns the number of groups
        evicted."""
        with self._lock:
            n = len(self._groups)
            self._groups.clear()
        if n:
            self.evictions += n
            from gatekeeper_tpu.utils.logging import log_event

            log_event("info", f"snapshot residency evicted {n} group(s)"
                      + (f": {reason}" if reason else ""),
                      event_type="residency_evicted", groups=n,
                      reason=reason)
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.inc_counter(M.RESIDENCY_EVICTIONS,
                                         value=float(n))
                self.metrics.set_gauge(M.SNAPSHOT_RESIDENT_BYTES,
                                       float(self.resident_bytes()))
        return n

    def invalidate(self) -> None:
        """Generation-swap seam (drivers/generation.py): new programs
        mean new schemas/layouts — drop the mirrors now instead of
        letting each group's uid check discover it one tick later."""
        self.evict_all("generation swap")

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(rg.resident_bytes for rg in self._groups.values())

    # --- sync ------------------------------------------------------------
    def _mask_rows(self, rg: ResidentGroup, batch, objects) -> np.ndarray:
        """[C, len(objects)] bool in constraint-grid order — the same
        ``constraint_masks`` call per kind the dispatch path makes, so
        per-object mask values are identical whether computed at patch
        time (here) or chunk time (the host reference lane)."""
        from gatekeeper_tpu.ir import masks as masks_mod

        if batch.has_generate_name is not None:
            any_gen = bool(
                batch.has_generate_name[: len(objects)].any())
        else:
            any_gen = any("generateName" in (o.get("metadata") or {})
                          for o in objects)
        rows = [masks_mod.constraint_masks(
            rg.by_kind[kind], batch, self.evaluator.driver.vocab,
            objects, any_generate_name=any_gen)
            for kind in rg.kinds]
        return np.concatenate(rows, axis=0)[:, : len(objects)]

    def _pack(self, store, positions, pad_n: int, rg: ResidentGroup):
        """(bufs, layout, batch, objects) for a row set, under the
        residency's stats-free layout (schema-only: no narrowing, no
        elision — the layout every sliver of the group reproduces)."""
        batch = store.slice_rows(positions, pad_n)
        objects = [store.row_obj(p) for p in positions]
        cols = slim_cols(pack_batch_cols(batch), rg.needs)
        bufs, layout = pack_transfer_cols(cols, pad_n, stats=None)
        return bufs, layout, batch, objects

    def _upload(self, store, rg: ResidentGroup) -> None:
        """Full upload: the tall packed buffers + the complete mask
        mirror.  Paid once per layout generation (boot, capacity growth,
        ragged widening, compaction, generation swap)."""
        import jax

        from gatekeeper_tpu.observability import tracing

        live = store.live_positions()
        with tracing.span("snapshot.residency.upload", rows=len(live),
                          cap=store.cap):
            # pack EVERY slot by position (dead slots ship stale bytes
            # under a False mask): device row index == store position,
            # the invariant chunk gathers and scatter-patches rely on
            bufs, layout, _batch, _objs = self._pack(
                store, list(range(store.n_rows)), store.cap, rg)
            rg.cols_dev = {dt: jax.device_put(b)
                           for dt, b in bufs.items()}
            rg.cols_layout = layout
            rg.cap = store.cap
            mask = np.zeros((rg.c_total, store.cap), bool)
            if live:
                lbatch = store.slice_rows(live, len(live))
                lobjs = [store.row_obj(p) for p in live]
                mask[:, live] = self._mask_rows(rg, lbatch, lobjs)
            rg.mask_host = mask
            rg.mask_dev = jax.device_put(mask)
            nbytes = sum(b.nbytes for b in bufs.values()) + mask.nbytes
            rg.resident_bytes = nbytes
            rg.idx_cache.clear()
            rg.mutation_mark = store.mutations
            rg.layout_version = store.layout_version
            store.patched.clear()
            self.h2d_bytes += nbytes
            self.upload_count += 1
        self.evaluator._perf_add("resident_h2d_bytes", float(nbytes))

    def _patch(self, store, rg: ResidentGroup) -> None:
        """Scatter-patch the dirty rows: sliver columns + sliver masks
        land with device ``.at[rows].set`` — H2D is O(patched rows)."""
        import jax.numpy as jnp

        from gatekeeper_tpu.observability import tracing

        patched = sorted(p for p in store.patched if p < store.n_rows)
        live = [p for p in patched if store.live[p]]
        dead = [p for p in patched if not store.live[p]]
        with tracing.span("snapshot.residency.patch", rows=len(patched)):
            nbytes = 0
            if live:
                bufs, layout, batch, objects = self._pack(
                    store, live, len(live), rg)
                if not _layout_equal(layout, rg.cols_layout):
                    # defensive: a sliver whose pack layout drifted from
                    # the tall layout (should be impossible under
                    # stats=None) re-uploads instead of corrupting rows
                    self._log_fallback("sliver layout drift (full "
                                      "re-upload)")
                    self._upload(store, rg)
                    return
                rows = np.asarray(live, np.intp)
                for dt, b in bufs.items():
                    rg.cols_dev[dt] = rg.cols_dev[dt].at[rows].set(b)
                    nbytes += b.nbytes
                m = self._mask_rows(rg, batch, objects)
                rg.mask_host[:, rows] = m
                rg.mask_dev = rg.mask_dev.at[:, rows].set(jnp.asarray(m))
                nbytes += m.nbytes + rows.nbytes
            if dead:
                rows = np.asarray(dead, np.intp)
                rg.mask_host[:, rows] = False
                rg.mask_dev = rg.mask_dev.at[:, rows].set(False)
                nbytes += rows.nbytes
            rg.mutation_mark = store.mutations
            store.patched.clear()
            self.h2d_bytes += nbytes
            self.patch_count += 1
        self.evaluator._perf_add("resident_h2d_bytes", float(nbytes))
        self.evaluator._perf_add("resident_dirty_rows", float(len(patched)))

    def prepare(self, store) -> Optional[ResidentGroup]:
        """Sync and return the device mirror for one GroupStore, or None
        when the host-column path must serve (reason logged once)."""
        if not self.available():
            return None
        if store.batch is None or not store.lowered:
            return None
        ev = self.evaluator
        progs = ev.driver._programs
        _bk, lowered, _schema = ev.sweep_schema(store.cons,
                                               programs=progs)
        kinds = tuple(sorted(lowered))
        if not kinds:
            return None
        from gatekeeper_tpu.ir.program import extdata_key_cols

        for kind in kinds:
            keymap, _ok = extdata_key_cols(progs[kind].program)
            if keymap:
                # external-data joins build per-chunk ext: tables off
                # the host batch — the resident lane has no host batch;
                # those groups keep host columns (ROADMAP NEXT)
                self._log_fallback("external-data joins (group keeps "
                                  "host columns)", kind=kind)
                return None
        uids = tuple(progs[kind].uid for kind in kinds)
        with self._lock:
            rg = self._groups.get(id(store))
            if rg is not None and (rg.store is not store
                                   or rg.uids != uids):
                rg = None
            if rg is None:
                by_kind = {k: [c for c in store.cons if c.kind == k]
                           for k in kinds}
                rg = ResidentGroup(
                    store, kinds, by_kind, uids,
                    ev._needs_union(kinds, store.alias, programs=progs))
                self._groups[id(store)] = rg
            if (rg.layout_version != store.layout_version
                    or rg.cap != store.cap or not rg.cols_dev):
                self._upload(store, rg)
            elif store.patched or rg.mutation_mark != store.mutations:
                self._patch(store, rg)
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.set_gauge(M.SNAPSHOT_RESIDENT_BYTES,
                                       float(self.resident_bytes()))
            return rg

    def stats(self) -> dict:
        return {"mode": self.mode,
                "groups": len(self._groups),
                "resident_bytes": self.resident_bytes(),
                "h2d_bytes": self.h2d_bytes,
                "uploads": self.upload_count,
                "patches": self.patch_count,
                "evictions": self.evictions}
