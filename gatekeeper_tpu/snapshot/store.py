"""Resident columnar cluster snapshot: sweep cost O(churn), not O(cluster).

Every relist-mode audit pass re-lists and re-flattens the whole cluster
(SWEEP1M: flatten alone is 13.9s of the 42.9s 1M-object sweep).  The
reference never does that — its watch manager / cachemanager keep a
synced cache and the audit reads from it (PAPER.md L1/L2:
``AddData``/``RemoveData`` on the Driver seam).  This module is the
columnar version of that cache:

- the flattened column arrays (plus vocab sids and canon columns) stay
  RESIDENT between sweeps, one tall :class:`ColumnBatch` per kind-group
  (the audit router's grouping, ``parallel/sharded.make_kind_router``);
- watch events apply as row-level patches: a new/changed object
  columnizes through the same flatten lane a fresh sweep would use and
  its row is written in place (or appended), deletes tombstone the row;
- a compaction step folds tombstones out when their fraction crosses a
  threshold — row POSITIONS move, row IDS do not
  (:class:`~gatekeeper_tpu.ops.flatten.RowIdMap`);
- the resident arrays slice straight into device sweep chunks
  (``ShardedEvaluator.sweep_flatten_from_batch``): a full snapshot pass
  pays zero list/flatten cost, an incremental tick evaluates only the
  dirty row set;
- :meth:`ClusterSnapshot.resync_differential` re-lists and re-flattens
  fresh and asserts the resident columns are bit-identical per row —
  the periodic proof that patch-maintained state equals rebuilt state.

The snapshot doubles as a warm inventory/namespace cache: every live
object is addressable by (gvk, namespace, name) without an apiserver
GET (:meth:`ClusterSnapshot.get`).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

import numpy as np

from gatekeeper_tpu.ops.flatten import ColumnBatch, KeySetColumn, \
    MapKeyColumn, ParentIdxColumn, RaggedColumn, RaggedKeySetColumn, \
    RowIdMap, RowInternCache, ScalarColumn, flatten_phase2
from gatekeeper_tpu.utils.rawjson import RawJSON, peek_kind
from gatekeeper_tpu.utils.unstructured import gvk_of, name_of, namespace_of

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class SnapshotConfig:
    # fold tombstoned rows out of a group's arrays once they exceed this
    # fraction of the group's slots (and the group is non-trivial)
    compact_tombstone_fraction: float = 0.25
    compact_min_rows: int = 64
    # pending watch events applied per flatten call (row patches
    # columnize in micro-batches so the C lane amortizes per-call cost)
    micro_batch: int = 512
    # phase-2 vocab interning keyed by stable global row ids
    # (ops.flatten.flatten_phase2): patch-lane flattens columnize against
    # a batch-local vocab and resolve strings the resident rows already
    # own from the RowInternCache — no per-occurrence probe of the
    # cluster-sized vocab dict, bit-identical ids
    phase2_intern: bool = True
    # a constraint-set / template (generation) change RE-CHUNKS resident
    # rows against the new plan instead of invalidating the whole
    # snapshot (zero relist; row ids survive); False = wholesale reset
    rechunk: bool = True


def obj_key(obj) -> tuple:
    """(gvk, namespace, name) — the snapshot's object identity (mirrors
    FakeCluster's store key; uids are not guaranteed off a real
    apiserver's test doubles)."""
    return (gvk_of(obj), namespace_of(obj), name_of(obj))


def resync_slice(key: tuple, phase: int, k: int) -> bool:
    """Rotor membership of an object key for the rotated resync
    differential: a stable content hash (crc32 of the canonical key
    repr) mod K — independent of gid assignment order and of Python's
    per-process string-hash seed, so the K slices partition the
    keyspace identically across restarts and both directions of the
    membership check agree."""
    return zlib.crc32(repr(key).encode()) % k == phase


# --- tall-batch array plumbing --------------------------------------------
#
# The resident store for one group IS a ColumnBatch whose row axis is a
# capacity (n == cap, rows beyond n_rows hold pad fills).  The helpers
# below enumerate every stored array with its pad fill so writes, growth,
# compaction and slicing share one definition of the layout.

_IDENTITY_FIELDS = ("group_sid", "kind_sid", "ns_sid", "name_sid")


def _iter_arrays(batch: ColumnBatch, skip=()):
    """Yield ``(path, array, fill)`` for every array of a batch.  ``path``
    is (family, spec, field) consumed by :func:`_get_arr`/:func:`_set_arr`;
    specs in ``skip`` (prefix-axis alias originals — they re-attach at
    slice time, sharing the exec arrays) are not yielded."""
    for spec, col in batch.scalars.items():
        yield ("scalars", spec, "kind"), col.kind, 0
        yield ("scalars", spec, "num"), col.num, 0.0
        yield ("scalars", spec, "sid"), col.sid, -1
    for spec, col in batch.raggeds.items():
        if spec in skip:
            continue
        yield ("raggeds", spec, "kind"), col.kind, 0
        yield ("raggeds", spec, "num"), col.num, 0.0
        yield ("raggeds", spec, "sid"), col.sid, -1
    for axis, cnt in batch.axis_counts.items():
        yield ("axis_counts", axis, None), cnt, 0
    for spec, col in batch.keysets.items():
        yield ("keysets", spec, "sid"), col.sid, -1
        yield ("keysets", spec, "count"), col.count, 0
    for spec, col in batch.ragged_keysets.items():
        if spec in skip:
            continue
        yield ("ragged_keysets", spec, "sid"), col.sid, -1
        yield ("ragged_keysets", spec, "count"), col.count, 0
    for spec, col in batch.map_keys.items():
        if spec in skip:
            continue
        yield ("map_keys", spec, "sid"), col.sid, -1
    for spec, col in batch.parent_idx.items():
        if spec in skip:
            continue
        yield ("parent_idx", spec, "idx"), col.idx, -1
    for spec, sids in batch.canons.items():
        yield ("canons", spec, None), sids, -2
    for name in _IDENTITY_FIELDS:
        yield ("ident", name, None), getattr(batch, name), -1
    yield ("ident", "has_generate_name", None), batch.has_generate_name, 0


_PLACEHOLDERS = {
    "scalars": lambda: ScalarColumn(None, None, None),
    "raggeds": lambda: RaggedColumn(None, None, None),
    "keysets": lambda: KeySetColumn(None, None),
    "ragged_keysets": lambda: RaggedKeySetColumn(None, None),
    "map_keys": lambda: MapKeyColumn(None),
    "parent_idx": lambda: ParentIdxColumn(None),
}

# pad fill per (family, field) — the static twin of the fills
# _iter_arrays yields off a live batch, used when a spilled (trimmed)
# array is re-padded back to capacity on load (snapshot/persist.py)
_FAM_FILLS = {
    ("scalars", "kind"): 0, ("scalars", "num"): 0.0,
    ("scalars", "sid"): -1,
    ("raggeds", "kind"): 0, ("raggeds", "num"): 0.0,
    ("raggeds", "sid"): -1,
    ("axis_counts", None): 0,
    ("keysets", "sid"): -1, ("keysets", "count"): 0,
    ("ragged_keysets", "sid"): -1, ("ragged_keysets", "count"): 0,
    ("map_keys", "sid"): -1,
    ("parent_idx", "idx"): -1,
    ("canons", None): -2,
}


def _fill_for(path):
    fam, spec, field = path
    if fam == "ident":
        return 0 if spec == "has_generate_name" else -1
    return _FAM_FILLS[(fam, field)]


def _set_arr(batch: ColumnBatch, path, arr) -> None:
    fam, spec, field = path
    if fam == "ident":
        setattr(batch, spec, arr)
        return
    d = getattr(batch, fam)
    if fam in ("axis_counts", "canons"):
        d[spec] = arr
        return
    if spec not in d:
        d[spec] = _PLACEHOLDERS[fam]()
    try:
        setattr(d[spec], field, arr)
    except dataclasses.FrozenInstanceError:  # e.g. ParentIdxColumn
        d[spec] = dataclasses.replace(d[spec], **{field: arr})


def _get_arr(batch: ColumnBatch, path):
    fam, spec, field = path
    if fam == "ident":
        return getattr(batch, spec)
    d = getattr(batch, fam)
    if fam in ("axis_counts", "canons"):
        return d[spec]
    return getattr(d[spec], field)


def row_signature(batch: ColumnBatch, i: int, skip=()) -> tuple:
    """Canonical per-row value tuple: every column family trimmed to the
    row's real extents (padding beyond an axis/keyset count is layout,
    not data).  Two batches flattened from the same object over the same
    vocab produce equal signatures regardless of pad widths — the unit
    of the resync differential's column comparison."""
    parts: list = []
    for name in _IDENTITY_FIELDS + ("has_generate_name",):
        arr = getattr(batch, name)
        parts.append(None if arr is None else int(arr[i]))
    counts: dict = {}
    for axis in sorted(batch.axis_counts, key=lambda a: a.key()):
        c = int(batch.axis_counts[axis][i])
        counts[axis] = c
        parts.append(("ax", axis.key(), c))
    for spec in sorted(batch.scalars, key=lambda s: s.path):
        col = batch.scalars[spec]
        parts.append(("sc", spec.path, int(col.kind[i]),
                      float(col.num[i]), int(col.sid[i])))
    for spec in sorted(batch.raggeds,
                       key=lambda r: (r.axis.key(), r.subpath)):
        if spec in skip:
            continue
        c = counts[spec.axis]
        col = batch.raggeds[spec]
        parts.append(("rg", spec.axis.key(), spec.subpath,
                      col.kind[i, :c].tobytes(), col.num[i, :c].tobytes(),
                      col.sid[i, :c].tobytes()))
    for spec in sorted(batch.keysets, key=lambda s: s.path):
        col = batch.keysets[spec]
        c = int(col.count[i])
        parts.append(("ks", spec.path, col.sid[i, :c].tobytes()))
    for spec in sorted(batch.ragged_keysets,
                       key=lambda r: (r.axis.key(), r.subpath)):
        if spec in skip:
            continue
        ac = counts[spec.axis]
        col = batch.ragged_keysets[spec]
        rows = tuple(col.sid[i, j, : int(col.count[i, j])].tobytes()
                     for j in range(ac))
        parts.append(("rks", spec.axis.key(), spec.subpath, rows))
    for spec in sorted(batch.map_keys, key=lambda m: m.axis.key()):
        if spec in skip:
            continue
        c = counts[spec.axis]
        parts.append(("mk", spec.axis.key(),
                      batch.map_keys[spec].sid[i, :c].tobytes()))
    for spec in sorted(batch.parent_idx,
                       key=lambda p: (p.axis.key(), p.parent.key())):
        if spec in skip:
            continue
        c = counts[spec.axis]
        parts.append(("pi", spec.axis.key(), spec.parent.key(),
                      batch.parent_idx[spec].idx[i, :c].tobytes()))
    for spec in sorted(batch.canons,
                       key=lambda c: (c.path, c.ns_scoped)):
        parts.append(("cn", spec.path, spec.ns_scoped,
                      int(batch.canons[spec][i])))
    return tuple(parts)


class GroupStore:
    """Resident columns + raw rows for one kind-group.

    ``group`` is the router's frozenset of template kinds; the empty
    group is the UNROUTED store (objects no template can match): raw rows
    only, counted in ``total_objects`` and servable from the warm cache,
    never flattened or evaluated."""

    def __init__(self, group: frozenset, constraints: Sequence,
                 evaluator, intern_cache=None):
        self.group = group
        self.cons = [c for c in constraints if c.kind in group]
        self.evaluator = evaluator
        # shared RowInternCache (phase-2 interning) or None = direct
        self.intern_cache = intern_cache
        if self.cons and evaluator is not None:
            _bk, lowered, schema = evaluator.sweep_schema(self.cons)
        else:
            lowered, schema = [], None
        self.lowered = tuple(sorted(lowered))
        self.schema = schema if self.lowered else None
        self.flattener = (evaluator._flattener(schema)
                          if self.lowered else None)
        self.alias = dict(self.flattener.alias) if self.flattener else {}
        self.batch: Optional[ColumnBatch] = None  # tall store, n == cap
        self.cap = 0
        self.n_rows = 0  # used slots (live + tombstoned), insertion order
        self.tombstones = 0
        self.objrefs: list = []  # per slot: bytes | dict | None (tomb)
        self.gids: list = []  # per slot: global row id
        self.live: list = []  # per slot: bool
        # device-residency + delta-spill bookkeeping: ``mutations`` is a
        # monotonic mark (any write/tombstone/compact/import bumps it —
        # delta spills skip groups whose mark hasn't moved),
        # ``layout_version`` bumps when array SHAPES change (growth,
        # ragged widening, compaction, import — a device mirror must
        # full-re-upload, scatter offsets no longer line up), and
        # ``patched`` holds positions dirtied since the device mirror
        # last synced (the scatter sliver; residency clears it)
        self.mutations = 0
        self.layout_version = 0
        self.patched: set = set()

    # --- row access ---------------------------------------------------
    @property
    def live_count(self) -> int:
        return self.n_rows - self.tombstones

    def live_positions(self) -> list:
        return [p for p in range(self.n_rows) if self.live[p]]

    def row_obj(self, pos: int):
        """The row's object: a lazy RawJSON over stored bytes, or the
        stored dict (watch events arrive parsed)."""
        ref = self.objrefs[pos]
        if isinstance(ref, (bytes, bytearray, memoryview)):
            return RawJSON(bytes(ref))
        return ref

    def row_signature(self, pos: int) -> tuple:
        return row_signature(self.batch, pos)

    def same_object(self, pos: int, obj) -> bool:
        """Cheap no-op-patch detection (watch replay after a 410 re-ADDs
        every object): identity, then resourceVersion, then deep
        equality."""
        ref = self.objrefs[pos]
        if ref is obj:
            return True
        try:
            if isinstance(ref, dict) and isinstance(obj, dict) \
                    and not isinstance(ref, RawJSON) \
                    and not isinstance(obj, RawJSON):
                rv_a = (ref.get("metadata") or {}).get("resourceVersion")
                rv_b = (obj.get("metadata") or {}).get("resourceVersion")
                if rv_a and rv_b:
                    return rv_a == rv_b
            return self.row_obj(pos) == obj
        except Exception:
            return False

    # --- writes -------------------------------------------------------
    def _grow_rows(self, need: int) -> None:
        if self.batch is None or need <= self.cap:
            return
        new_cap = max(64, self.cap)
        while new_cap < need:
            new_cap *= 2
        for path, arr, fill in list(_iter_arrays(self.batch)):
            new = np.full((new_cap,) + arr.shape[1:], fill, arr.dtype)
            new[: self.cap] = arr
            _set_arr(self.batch, path, new)
        self.cap = new_cap
        self.batch.n = new_cap
        self.layout_version += 1

    def _init_base(self, local: ColumnBatch, need: int) -> None:
        cap = 64
        while cap < need:
            cap *= 2
        base = ColumnBatch(n=cap, scalars={}, raggeds={}, axis_counts={},
                           keysets={})
        for path, arr, fill in _iter_arrays(local, skip=self.alias):
            if arr is None:
                continue
            _set_arr(base, path, np.full((cap,) + arr.shape[1:], fill,
                                         arr.dtype))
        self.batch = base
        self.cap = cap
        self.layout_version += 1

    def _write_rows(self, local: ColumnBatch, positions: Sequence[int],
                    k: int) -> None:
        """Write the first ``k`` rows of ``local`` into base rows
        ``positions``, reconciling ragged widths (the base keeps the
        running max; narrower patch rows pad with the family fill)."""
        idx = np.asarray(positions, np.intp)
        for path, arr, fill in _iter_arrays(local, skip=self.alias):
            if arr is None:
                continue
            base_arr = _get_arr(self.batch, path)
            if base_arr.shape[1:] != arr.shape[1:]:
                tail = tuple(max(a, b) for a, b in
                             zip(base_arr.shape[1:], arr.shape[1:]))
                if tail != base_arr.shape[1:]:
                    wider = np.full((self.cap,) + tail, fill,
                                    base_arr.dtype)
                    region = (slice(None),) + tuple(
                        slice(0, s) for s in base_arr.shape[1:])
                    wider[region] = base_arr
                    _set_arr(self.batch, path, wider)
                    base_arr = wider
                    self.layout_version += 1
            base_arr[idx] = fill  # reset the full row (old wide values)
            region = (idx,) + tuple(slice(0, s) for s in arr.shape[1:])
            base_arr[region] = arr[:k]

    def write(self, entries: Sequence[tuple]) -> list:
        """Apply a micro-batch of upserts.  ``entries`` is
        ``[(pos_or_None, gid, obj)]``; returns the base position per
        entry (appends allocate).  Routed groups columnize the batch
        through the SAME flattener a fresh sweep of this group would use
        — the bit-identity precondition."""
        objs = [obj for _pos, _gid, obj in entries]
        positions: list = []
        n_new = sum(1 for pos, _g, _o in entries if pos is None)
        need = self.n_rows + n_new
        if self.flattener is not None:
            if self.intern_cache is not None:
                local = flatten_phase2(
                    self.flattener, objs,
                    [gid for _pos, gid, _obj in entries],
                    self.intern_cache)
            else:
                local = self.flattener.flatten(objs)
            if local.has_generate_name is None:
                local.has_generate_name = np.array(
                    [1 if "generateName" in (o.get("metadata") or {})
                     else 0 for o in objs], np.uint8)
            if self.batch is None:
                self._init_base(local, need)
            elif need > self.cap:
                self._grow_rows(need)
        for pos, gid, obj in entries:
            if pos is None:
                pos = self.n_rows
                self.n_rows += 1
                self.objrefs.append(None)
                self.gids.append(gid)
                self.live.append(True)
            ref = obj.raw if isinstance(obj, RawJSON) and not obj._loaded \
                else obj
            self.objrefs[pos] = ref
            self.gids[pos] = gid
            self.live[pos] = True
            positions.append(pos)
        if self.flattener is not None:
            self._write_rows(local, positions, len(entries))
        self.mutations += 1
        self.patched.update(positions)
        return positions

    def tombstone(self, pos: int) -> None:
        if not self.live[pos]:
            return
        self.live[pos] = False
        self.objrefs[pos] = None
        self.tombstones += 1
        self.mutations += 1
        self.patched.add(pos)

    def needs_compaction(self, cfg: SnapshotConfig) -> bool:
        return (self.n_rows >= cfg.compact_min_rows
                and self.tombstones > 0
                and self.tombstones / self.n_rows
                >= cfg.compact_tombstone_fraction)

    def compact(self) -> dict:
        """Fold tombstones out, preserving row order.  Returns
        {gid: new_pos} for the survivors (row IDS are stable — only
        positions move)."""
        keep = self.live_positions()
        k = len(keep)
        if self.batch is not None and k:
            kidx = np.asarray(keep, np.intp)
            for path, arr, fill in list(_iter_arrays(self.batch)):
                moved = arr[kidx]
                arr[:] = fill
                arr[:k] = moved
        elif self.batch is not None:
            for path, arr, fill in _iter_arrays(self.batch):
                arr[:] = fill
        self.objrefs = [self.objrefs[p] for p in keep]
        self.gids = [self.gids[p] for p in keep]
        self.live = [True] * k
        self.n_rows = k
        self.tombstones = 0
        self.mutations += 1
        self.layout_version += 1  # positions moved: scatter can't patch
        self.patched.clear()
        return {self.gids[i]: i for i in range(k)}

    # --- reads (the sweep lane) ---------------------------------------
    def slice_rows(self, positions: Sequence[int], pad_n: int) -> \
            ColumnBatch:
        """Gather rows into a chunk-shaped ColumnBatch (pad rows carry
        the same fills a fresh flatten's pad region would).  Prefix-axis
        aliases re-attach sharing the gathered arrays, so the wire
        packer's identity dedup still fires."""
        k = len(positions)
        idx = np.asarray(positions, np.intp)
        out = ColumnBatch(n=pad_n, scalars={}, raggeds={}, axis_counts={},
                          keysets={})
        for path, arr, fill in _iter_arrays(self.batch):
            sl = np.full((pad_n,) + arr.shape[1:], fill, arr.dtype)
            if k:
                sl[:k] = arr[idx]
            _set_arr(out, path, sl)
        if self.flattener is not None:
            self.flattener._apply_alias(out)
        return out

    # --- spill (snapshot/persist.py) ----------------------------------
    def schema_digest(self) -> str:
        """Digest of this group's columnize plan — the load-time guard
        that a spilled group's arrays still mean what the CURRENT
        template set's schemas say they mean (template drift with an
        unchanged constraint spec would otherwise misread columns)."""
        from gatekeeper_tpu.drivers.generation import schema_digest

        return schema_digest(self.schema)

    def export_rows(self) -> dict:
        """Spill payload of one group: every stored array trimmed to the
        used slots (capacity padding is layout, not data — it re-pads on
        load), plus the slot bookkeeping and raw object refs.  Array
        copies happen here, under the snapshot lock; pickling happens
        off-thread."""
        n = self.n_rows
        arrays: dict = {}
        if self.batch is not None:
            for path, arr, _fill in _iter_arrays(self.batch):
                arrays[path] = np.ascontiguousarray(arr[:n])
        refs: list = []
        for ref in self.objrefs:
            if ref is None:
                refs.append(None)
            elif isinstance(ref, (bytes, bytearray, memoryview)):
                refs.append(bytes(ref))
            elif isinstance(ref, RawJSON):
                refs.append(bytes(ref.raw))
            else:
                refs.append(ref)
        return {
            "kinds": sorted(self.group),
            "lowered": list(self.lowered),
            "schema": self.schema_digest(),
            "n_rows": n,
            "gids": list(self.gids),
            "live": list(self.live),
            "objrefs": refs,
            "arrays": arrays,
            "mutations": self.mutations,
        }

    def import_rows(self, payload: dict) -> None:
        """Adopt a spilled group's rows into this (freshly constructed)
        store: re-pad the trimmed arrays to a pow2 capacity with the
        family fills.  The caller validated ``schema``/``lowered``
        against this store's freshly derived plan first — arrays written
        under a different plan must never be adopted."""
        n = int(payload["n_rows"])
        arrays = payload["arrays"]
        if arrays:
            cap = 64
            while cap < n:
                cap *= 2
            base = ColumnBatch(n=cap, scalars={}, raggeds={},
                               axis_counts={}, keysets={})
            for path, arr in arrays.items():
                full = np.full((cap,) + arr.shape[1:], _fill_for(path),
                               arr.dtype)
                full[:n] = arr
                _set_arr(base, path, full)
            self.batch = base
            self.cap = cap
        self.n_rows = n
        self.gids = list(payload["gids"])
        self.live = list(payload["live"])
        self.objrefs = list(payload["objrefs"])
        self.tombstones = sum(1 for alive in self.live if not alive)
        # resume the spiller's mutation clock so the first post-boot
        # delta spill still skips groups that haven't moved since
        self.mutations = int(payload.get("mutations", 0)) + 1
        self.layout_version += 1
        self.patched.clear()


def concat_group_rows(parts: Sequence[tuple], pad_n: int) -> ColumnBatch:
    """Gather rows of SEVERAL same-plan GroupStores into one packed
    chunk-shaped :class:`ColumnBatch` — the fleet packer's batch
    builder (``fleet/evaluator.py``): K small clusters' same-group rows
    ride one device dispatch instead of K underfilled ones.

    ``parts`` is ``[(store, positions)]``; segments land in order, so
    every cluster's rows keep their canonical row order inside the
    packed batch (the bit-identity precondition the per-cluster fold
    relies on).  Per array path the widest tail wins — ragged pad
    widths are data-dependent per store, and narrower segments pad
    with the family fill, exactly the reconciliation
    :meth:`GroupStore._write_rows` applies.  Pad rows beyond the real
    rows carry the same fills a fresh flatten's pad region would.
    Prefix-axis aliases re-attach off the first store's flattener.
    The caller guarantees the stores share one columnize plan (same
    library runtime, same constraint group — same schema digest)."""
    paths: dict = {}  # path -> [tail, dtype, fill]
    arrs: list = []   # per part: {path: array}
    for store, _positions in parts:
        per: dict = {}
        for path, arr, fill in _iter_arrays(store.batch):
            if arr is None:
                continue
            per[path] = arr
            prev = paths.get(path)
            if prev is None:
                paths[path] = [arr.shape[1:], arr.dtype, fill]
            else:
                prev[0] = tuple(max(a, b) for a, b in
                                zip(prev[0], arr.shape[1:]))
        arrs.append(per)
    out = ColumnBatch(n=pad_n, scalars={}, raggeds={}, axis_counts={},
                      keysets={})
    for path, (tail, dtype, fill) in paths.items():
        full = np.full((pad_n,) + tuple(tail), fill, dtype)
        off = 0
        for (store, positions), per in zip(parts, arrs):
            k = len(positions)
            arr = per.get(path)
            if k and arr is not None:
                idx = np.asarray(positions, np.intp)
                region = (slice(off, off + k),) + tuple(
                    slice(0, s) for s in arr.shape[1:])
                full[region] = arr[idx]
            off += k
        _set_arr(out, path, full)
    fl = parts[0][0].flattener
    if fl is not None:
        fl._apply_alias(out)
    return out


class VerdictStore:
    """Per-(constraint, row) audit results, keyed by stable row id.

    ``count`` is the row's contribution to the constraint's
    totalViolations (result count in exact-totals mode, 1 otherwise);
    ``msgs`` is the rendered ``(message, details)`` tuple — None until a
    kept-list derivation renders it (lazy in non-exact mode)."""

    def __init__(self):
        self._rows: dict = {}  # con_key -> {gid: [count, msgs|None]}
        self._by_gid: dict = {}  # gid -> set(con_key)

    def set(self, con_key, gid: int, count: int, msgs) -> None:
        self._rows.setdefault(con_key, {})[gid] = [count, msgs]
        self._by_gid.setdefault(gid, set()).add(con_key)

    def set_msgs(self, con_key, gid: int, msgs) -> None:
        self._rows[con_key][gid][1] = msgs

    def clear_gid(self, gid: int) -> None:
        for con_key in self._by_gid.pop(gid, ()):
            rows = self._rows.get(con_key)
            if rows is not None:
                rows.pop(gid, None)

    def rows(self, con_key) -> list:
        """[(gid, count, msgs)] in stable row-id (= insertion) order."""
        rows = self._rows.get(con_key, {})
        return [(gid, v[0], v[1]) for gid, v in sorted(rows.items())]

    def total(self, con_key) -> int:
        return sum(v[0] for v in self._rows.get(con_key, {}).values())

    def clear(self) -> None:
        self._rows.clear()
        self._by_gid.clear()

    def export_state(self) -> list:
        """[(con_key, [(gid, count, msgs)])] — the spill's verdict
        section (rendered msgs ride along so a warm boot's first kept
        derivation pays zero renders for already-rendered rows)."""
        return [(ck, [(gid, v[0], v[1]) for gid, v in rows.items()])
                for ck, rows in self._rows.items()]

    def restore(self, state: list) -> None:
        """Bulk-build the maps (a 20k-row spill carries ~100k verdict
        entries; per-entry ``set()`` calls measured 0.5s of the 1s
        load — dict comprehensions do the same work in ~0.1s)."""
        self._rows = {ck: {gid: [count, msgs]
                           for gid, count, msgs in rows}
                      for ck, rows in state}
        by_gid: dict = {}
        for ck, rows in self._rows.items():
            for gid in rows:
                hit = by_gid.get(gid)
                if hit is None:
                    by_gid[gid] = {ck}
                else:
                    hit.add(ck)
        self._by_gid = by_gid


class ClusterSnapshot:
    """The process-wide resident snapshot: groups + identity + dirty set.

    Thread model: watch callbacks only ENQUEUE (lock-free deque append);
    all state mutation happens in :meth:`pump`/:meth:`rebuild` on the
    audit thread under ``self.lock``.  Reads used by the webhook warm
    cache (:meth:`get`) take the same lock briefly."""

    def __init__(self, evaluator, config: Optional[SnapshotConfig] = None,
                 metrics=None):
        self.evaluator = evaluator
        self.config = config or SnapshotConfig()
        self.metrics = metrics
        self.lock = threading.RLock()
        self.ids = RowIdMap()
        self.verdicts = VerdictStore()
        # phase-2 interning (ops.flatten.flatten_phase2), keyed by the
        # RowIdMap's stable gids; None disables (direct global interning)
        self.intern_cache = RowInternCache() \
            if self.config.phase2_intern else None
        self._groups: dict = {}  # frozenset -> GroupStore
        self._router = None
        self._constraints: list = []
        self._digest = None
        self._pos: dict = {}  # gid -> (GroupStore, pos)
        self._dirty: set = set()  # gids pending (re)evaluation
        self._pending: deque = deque()  # (etype, obj) from watch callbacks
        self.stale = True  # needs a rebuild before serving sweeps
        self.generation = 0
        self.patch_count = 0
        self.rechunk_count = 0  # plan changes absorbed without a relist
        # True after adopt_spill: the resident state came off a disk
        # spill (snapshot/persist.py) — the audit loop's FIRST pass can
        # be an incremental tick (rows are clean, verdicts persisted)
        # instead of the O(cluster) full build+evaluate
        self.warm_loaded = False

    # --- constraint set currency ---------------------------------------
    def _cons_digest(self, constraints) -> tuple:
        spec = tuple(sorted(
            (c.kind, c.name,
             json.dumps(c.raw.get("spec", {}), sort_keys=True, default=str)
             if isinstance(c.raw, dict) else "")
            for c in constraints))
        lowered: tuple = ()
        if self.evaluator is not None:
            _bk, low, _schema = self.evaluator.sweep_schema(constraints)
            lowered = tuple(sorted(low))
        return (spec, lowered)

    def set_constraints(self, constraints: Sequence) -> bool:
        """Adopt the active constraint set; a changed set (or a lowering/
        inventory-exactness flip) invalidates the derived state — groups,
        schemas and verdicts.  Returns True when a full rebuild (relist)
        is now required.

        When the snapshot already holds resident rows, a plan change
        (template edit / generation swap / constraint churn) RE-CHUNKS
        instead: the resident raw objects re-columnize against the new
        plan's schemas with their row ids intact and every row marked
        dirty — O(cluster) flatten+eval once, but zero relist traffic
        and no identity loss.  ``SnapshotConfig.rechunk=False`` keeps
        the wholesale reset."""
        from gatekeeper_tpu.parallel.sharded import make_kind_router

        digest = self._cons_digest(constraints)
        with self.lock:
            if digest == self._digest and not self.stale:
                return False
            if digest != self._digest:
                can_rechunk = (getattr(self.config, "rechunk", True)
                               and not self.stale and self._pos)
                self._digest = digest
                self._constraints = list(constraints)
                self._router = make_kind_router(constraints)
                if can_rechunk and self._rechunk():
                    return False
                self._reset_rows()
            return self.stale

    def _rechunk(self) -> bool:
        """Re-columnize every resident row against the NEW plan (new
        router, new group schemas from the freshly-swapped generation).
        Row ids survive (``_apply_upserts`` re-appends a known id whose
        position was cleared); verdicts reset and every routed row lands
        dirty, so the next tick re-evaluates the cluster against the new
        template set without a relist.  Returns False (fall back to the
        wholesale reset) when any resident object is unavailable."""
        from gatekeeper_tpu.observability import tracing

        objs: list = []
        for store in self._groups.values():
            for pos in store.live_positions():
                obj = store.row_obj(pos)
                if obj is None:
                    return False
                objs.append((store.gids[pos], obj))
        with tracing.span("snapshot.rechunk", rows=len(objs)):
            # gid order: deterministic write order regardless of the old
            # grouping (ids are monotone arrival order)
            objs.sort(key=lambda t: t[0])
            self._groups = {}
            self._pos = {}
            self._dirty = set()
            self.verdicts.clear()
            if self.intern_cache is not None:
                self.intern_cache.clear()
            mb = max(1, self.config.micro_batch)
            pending = [(obj_key(o), o) for _gid, o in objs]
            for i in range(0, len(pending), mb):
                self._apply_upserts(pending[i: i + mb])
            self.rechunk_count += 1
            self.generation += 1
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.inc_counter(M.SNAPSHOT_PATCHES,
                                         {"type": "rechunk"},
                                         value=float(len(pending)))
        return True

    def invalidate(self) -> None:
        """Force a rebuild before the next sweep (resync divergence)."""
        with self.lock:
            self.stale = True

    def _reset_rows(self) -> None:
        self._groups = {}
        self._pos = {}
        self._dirty = set()
        self.verdicts.clear()
        if self.intern_cache is not None:
            self.intern_cache.clear()
        self.stale = True

    def _store_for(self, kind: str) -> GroupStore:
        g = self._router(kind) if self._router is not None else frozenset()
        store = self._groups.get(g)
        if store is None:
            store = GroupStore(g, self._constraints, self.evaluator,
                               intern_cache=self.intern_cache)
            self._groups[g] = store
        return store

    # --- ingest ---------------------------------------------------------
    def enqueue(self, etype: str, obj) -> None:
        """Watch-callback side: queue only (applied by :meth:`pump`)."""
        self._pending.append((etype, obj))

    def pending_count(self) -> int:
        return len(self._pending)

    def pump(self, max_events: Optional[int] = None) -> int:
        """Apply queued watch events as row patches.  Events coalesce to
        the LAST event per object key (an upsert is a full-row write and
        a delete removes the row, so intermediate states are dead);
        upserts columnize per group in micro-batches through the raw
        patch lane."""
        from gatekeeper_tpu.observability import tracing

        drained: list = []
        while self._pending and (max_events is None
                                 or len(drained) < max_events):
            drained.append(self._pending.popleft())
        if not drained:
            return 0
        with tracing.span("snapshot.pump", events=len(drained)):
            final: dict = {}  # key -> (etype, obj), insertion-ordered
            for etype, obj in drained:
                key = obj_key(obj)
                final.pop(key, None)
                final[key] = (etype, obj)
            with self.lock:
                upserts: list = []
                for key, (etype, obj) in final.items():
                    if etype == DELETED:
                        self._delete(key)
                    else:
                        upserts.append((key, obj))
                self._apply_upserts(upserts)
                self._maybe_compact()
        return len(drained)

    def _delete(self, key) -> None:
        gid = self.ids.get(key)
        if gid is None:
            return
        self.ids.forget(key)
        store, pos = self._pos.pop(gid)
        store.tombstone(pos)
        if self.intern_cache is not None:
            self.intern_cache.forget(gid)
        self.verdicts.clear_gid(gid)
        self._dirty.discard(gid)
        self.patch_count += 1
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(M.SNAPSHOT_PATCHES,
                                     {"type": "delete"})

    def _apply_upserts(self, upserts: Sequence[tuple]) -> None:
        """Route + columnize + write a list of (key, obj) upserts, in
        micro-batches per group.  Unchanged objects (watch replay churn
        after a 410) are detected and skipped — no dirty marking, no
        flatten."""
        by_store: dict = {}
        for key, obj in upserts:
            kind = peek_kind(obj)
            store = self._store_for(kind)
            gid = self.ids.get(key)
            pos = None
            if gid is not None:
                entry = self._pos.get(gid)
                if entry is None:
                    # identity survives a rebuild's row reset
                    # (RowIdMap persistence): the row re-appends under
                    # its existing id
                    pass
                else:
                    cur_store, pos = entry
                    if cur_store is store and store.same_object(pos, obj):
                        continue  # no-op patch
                    if cur_store is not store:
                        # kind collision across groups cannot happen for
                        # one key (kind is part of the key); defensive
                        # reset
                        self._delete(key)
                        gid, pos = None, None
            created = False
            if gid is None:
                gid, created = self.ids.assign(key)
            by_store.setdefault(id(store), (store, []))[1].append(
                (pos, gid, obj, created))
        mb = max(1, self.config.micro_batch)
        n_add = n_mod = 0
        for store, entries in by_store.values():
            for i in range(0, len(entries), mb):
                batch = entries[i: i + mb]
                positions = store.write(
                    [(pos, gid, obj) for pos, gid, obj, _c in batch])
                for (pos0, gid, _obj, created), pos in zip(batch,
                                                           positions):
                    self._pos[gid] = (store, pos)
                    if store.cons:
                        self._dirty.add(gid)
                    self.patch_count += 1
                    if created:
                        n_add += 1
                    else:
                        n_mod += 1
        if self.metrics is not None and (n_add or n_mod):
            from gatekeeper_tpu.metrics import registry as M

            if n_add:
                self.metrics.inc_counter(M.SNAPSHOT_PATCHES,
                                         {"type": "add"}, value=n_add)
            if n_mod:
                self.metrics.inc_counter(M.SNAPSHOT_PATCHES,
                                         {"type": "modify"}, value=n_mod)

    def _maybe_compact(self) -> None:
        for store in self._groups.values():
            if store.needs_compaction(self.config):
                remap = store.compact()
                for gid, pos in remap.items():
                    self._pos[gid] = (store, pos)

    # --- rebuild ---------------------------------------------------------
    def rebuild(self, lister) -> int:
        """Full relist into fresh stores (initial build, and the recovery
        path after a resync divergence).  Row ids of surviving keys are
        stable across rebuilds (RowIdMap persistence).  Returns the row
        count."""
        from gatekeeper_tpu.observability import tracing

        with tracing.span("snapshot.rebuild"), self.lock:
            self._reset_rows()
            seen: set = set()
            batch: list = []
            mb = max(1, self.config.micro_batch)
            for obj in lister():
                batch.append((obj_key(obj), obj))
                if len(batch) >= mb:
                    seen.update(k for k, _o in batch)
                    self._apply_upserts(batch)
                    batch = []
            if batch:
                seen.update(k for k, _o in batch)
                self._apply_upserts(batch)
            # keys known from a previous generation but absent now: the
            # reset already dropped their rows, only the identity lingers
            for key in [k for k in self.ids.uids() if k not in seen]:
                self.ids.forget(key)
            self.stale = False
            self.generation += 1
            return self.live_count()

    # --- spill export / adopt (snapshot/persist.py) ----------------------
    def export_state(self, known_marks: Optional[dict] = None) -> dict:
        """Capture the complete resident state for a disk spill, under
        the lock: group arrays (trimmed copies), identity map, verdicts,
        dirty set, constraint digest.  The capture copies every array
        (memcpy-fast) so the caller can pickle + write OFF the audit
        thread without holding the lock.

        ``known_marks`` (delta spills) maps a group's kinds-key
        (``"|".join(sorted(kinds))``) to the mutation mark the spiller
        last wrote; groups whose mark hasn't moved export a SKIPPED stub
        (no array copies) and the spiller reuses the on-disk section."""
        with self.lock:
            groups = []
            for store in self._groups.values():
                key = "|".join(sorted(store.group))
                if known_marks is not None \
                        and known_marks.get(key) == store.mutations:
                    groups.append({"kinds": sorted(store.group),
                                   "mutations": store.mutations,
                                   "skipped": True})
                else:
                    groups.append(store.export_rows())
            return {
                "digest": self._digest,
                "ids": self.ids.export_state(),
                "dirty": sorted(self._dirty),
                "verdicts": self.verdicts.export_state(),
                "groups": groups,
                "rows": self.live_count(),
            }

    def adopt_spill(self, constraints: Sequence, state: dict) -> int:
        """Install a validated spill: fresh GroupStores re-derive their
        schemas from the LIVE constraint set, adopt the spilled arrays,
        and every loaded row is clean with its persisted verdicts — the
        next tick serves resident rows with zero relist and zero
        flatten.  Raises ``ValueError`` (nothing committed) when any
        group's freshly derived plan disagrees with the plan its arrays
        were written under; the caller treats that as a spill miss."""
        from gatekeeper_tpu.parallel.sharded import make_kind_router

        router = make_kind_router(constraints)
        cons = list(constraints)
        stores: dict = {}
        pos: dict = {}
        for payload in state["groups"]:
            g = frozenset(payload["kinds"])
            store = GroupStore(g, cons, self.evaluator,
                               intern_cache=self.intern_cache)
            if list(store.lowered) != list(payload["lowered"]):
                raise ValueError(
                    f"group {sorted(g)!r}: lowered set drifted")
            if store.lowered and \
                    store.schema_digest() != payload["schema"]:
                raise ValueError(
                    f"group {sorted(g)!r}: schema digest drifted")
            store.import_rows(payload)
            stores[g] = store
            for p, (gid, alive) in enumerate(zip(store.gids, store.live)):
                if alive:
                    pos[gid] = (store, p)
        with self.lock:
            self._digest = state["digest"]
            self._constraints = cons
            self._router = router
            self._groups = stores
            self._pos = pos
            self.ids.restore(state["ids"])
            self.verdicts.restore(state["verdicts"])
            if self.intern_cache is not None:
                self.intern_cache.clear()
            self._dirty = set(state["dirty"])
            self.stale = False
            self.warm_loaded = True
            self.generation += 1
            return self.live_count()

    def keys_for_gvk(self, gvk: tuple) -> list:
        """(namespace, name) keys of every known object of one GVK — the
        seed for a warm watch resubscription's vanished-object diff (a
        410 relist must synthesize DELETED for spilled rows the fresh
        list no longer carries)."""
        with self.lock:
            return [(ns, name) for (g, ns, name) in self.ids.uids()
                    if g == gvk]

    # --- sweep-facing reads ----------------------------------------------
    def routed_stores(self) -> list:
        return [s for s in self._groups.values() if s.cons]

    def all_rows(self) -> dict:
        """{GroupStore: [(gid, pos)] in row order} over every live routed
        row (the full snapshot pass)."""
        out: dict = {}
        with self.lock:
            for store in self.routed_stores():
                out[store] = [(store.gids[p], p)
                              for p in store.live_positions()]
        return out

    def dirty_rows(self) -> dict:
        """{GroupStore: [(gid, pos)]} for the dirty set only (the
        incremental tick)."""
        out: dict = {}
        with self.lock:
            for gid in sorted(self._dirty):
                store, pos = self._pos[gid]
                if store.live[pos]:
                    out.setdefault(store, []).append((gid, pos))
        return out

    def mark_clean(self, gids: Iterable[int]) -> None:
        with self.lock:
            self._dirty.difference_update(gids)

    def dirty_count(self) -> int:
        return len(self._dirty)

    def live_count(self) -> int:
        with self.lock:
            return sum(s.live_count for s in self._groups.values())

    def obj_of(self, gid: int):
        """Live object of a global row id, or None when the row was
        deleted (tombstoned or compacted away) — callers use the None
        to retire per-gid state (e.g. generated-resultant verdicts)."""
        with self.lock:
            hit = self._pos.get(gid)
            if hit is None:
                return None
            store, pos = hit
            return store.row_obj(pos)

    # --- warm cache (webhook referential/namespace lookups) -------------
    def get(self, gvk: tuple, namespace: str, name: str):
        """Resident object lookup — the webhook's warm inventory cache
        (no apiserver GET).  Returns None when absent OR when the
        snapshot is stale (callers fall back to their own source)."""
        with self.lock:
            if self.stale:
                return None
            gid = self.ids.get((gvk, namespace, name))
            if gid is None:
                return None
            store, pos = self._pos[gid]
            return store.row_obj(pos)

    def namespace(self, name: str):
        return self.get(("", "v1", "Namespace"), "", name)

    # --- resync differential ---------------------------------------------
    def resync_differential(self, lister,
                            rotor: Optional[tuple] = None
                            ) -> Optional[str]:
        """Re-list + re-flatten fresh and compare against the resident
        columns row by row: membership, routing, and the full per-row
        column signature (identity, counts, every family trimmed to real
        extents, canon sids).  The fresh flatten runs over the SAME vocab
        — by resync time every string is interned, so a vocab that grows
        here is itself a divergence.  Returns None when bit-identical,
        else a first-difference description.  O(cluster) by design (the
        periodic proof).

        ``rotor=(phase, K)`` restricts the proof to the 1/K slice of the
        keyspace whose deterministic key hash lands on ``phase``
        (:func:`resync_slice`): only slice objects re-flatten and only
        slice identities must be present/absent, so K consecutive
        rotated calls cover every row at ~1/K the re-flatten cost each
        (``--snapshot-resync-rotate``).  The hash keys on the object
        key, not the gid, so membership-in-slice is stable for rows the
        snapshot has never seen (a missed add diverges within K
        intervals)."""
        from gatekeeper_tpu.observability import tracing

        with tracing.span("snapshot.resync"), self.lock:
            vocab = self.evaluator.driver.vocab
            vocab0 = len(vocab)
            flatteners: dict = {}
            bufs: dict = {}
            seen: set = set()
            diff: list = []

            def check_chunk(store, objs, keys):
                fl = flatteners.get(id(store))
                if fl is None:
                    fl = self.evaluator._flattener(store.schema)
                    flatteners[id(store)] = fl
                fb = fl.flatten(objs)
                if fb.has_generate_name is None:
                    # dict-lane flatten derives no presence column; the
                    # store normalizes it at write time — mirror that
                    fb.has_generate_name = np.array(
                        [1 if "generateName" in (o.get("metadata") or {})
                         else 0 for o in objs], np.uint8)
                skip = set(fl.alias)
                for i, key in enumerate(keys):
                    gid = self.ids.get(key)
                    if gid is None:
                        diff.append(f"row {key!r} missing from snapshot")
                        return
                    cur, pos = self._pos[gid]
                    if cur is not store:
                        diff.append(f"row {key!r} routed to a different "
                                    f"group")
                        return
                    if row_signature(fb, i, skip=skip) != \
                            cur.row_signature(pos):
                        diff.append(f"columns differ for row {key!r}")
                        return

            for obj in lister():
                key = obj_key(obj)
                if rotor is not None and \
                        not resync_slice(key, rotor[0], rotor[1]):
                    continue  # out of rotation this interval
                seen.add(key)
                if diff:
                    break
                kind = peek_kind(obj)
                store = self._store_for(kind)
                if not store.cons:
                    if self.ids.get(key) is None:
                        diff.append(
                            f"unrouted row {key!r} missing from snapshot")
                        break
                    continue
                buf = bufs.setdefault(id(store), (store, [], []))
                buf[1].append(obj)
                buf[2].append(key)
                if len(buf[1]) >= max(1, self.config.micro_batch):
                    check_chunk(store, buf[1], buf[2])
                    bufs[id(store)] = (store, [], [])
            if not diff:
                for store, objs, keys in bufs.values():
                    if objs and not diff:
                        check_chunk(store, objs, keys)
            if not diff:
                extra = [k for k in self.ids.uids() if k not in seen
                         and (rotor is None
                              or resync_slice(k, rotor[0], rotor[1]))]
                if extra:
                    diff.append(f"snapshot row {extra[0]!r} not in the "
                                f"fresh relist")
            if not diff and len(vocab) != vocab0:
                diff.append(f"fresh relist interned {len(vocab) - vocab0} "
                            f"new vocab entries")
            return diff[0] if diff else None

    # --- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self.lock:
            slots = sum(s.n_rows for s in self._groups.values())
            tombs = sum(s.tombstones for s in self._groups.values())
            return {
                "rows": self.live_count(),
                "dirty_rows": len(self._dirty),
                "tombstone_fraction": (tombs / slots) if slots else 0.0,
                "patch_count": self.patch_count,
                "groups": len(self._groups),
                "generation": self.generation,
                "pending_events": len(self._pending),
            }

    def publish_metrics(self) -> None:
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as M

        st = self.stats()
        self.metrics.set_gauge(M.SNAPSHOT_ROWS, st["rows"])
        self.metrics.set_gauge(M.SNAPSHOT_DIRTY, st["dirty_rows"])
        self.metrics.set_gauge(M.SNAPSHOT_TOMBSTONE_FRACTION,
                               st["tombstone_fraction"])
        if self.intern_cache is not None:
            self.metrics.set_gauge(M.SNAPSHOT_INTERN_HITS,
                                   self.intern_cache.hits)
            self.metrics.set_gauge(M.SNAPSHOT_INTERN_PROBES,
                                   self.intern_cache.probes)
