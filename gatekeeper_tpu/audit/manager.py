"""Audit sweep: the 1M-object enforcement point.

Reference flow (pkg/audit/manager.go:258-973, SURVEY.md §3.2):
list every auditable object (chunked) → review each against all constraints →
keep top-K violations per constraint (LimitQueue) → write constraint status +
export + logs.

TPU-native middle: each chunk flattens to columns and the whole
constraint × chunk grid evaluates in one sharded device pass
(parallel/sharded.ShardedEvaluator); only the ≤K kept violations per
constraint are rendered to messages through the exact interpreter.  Fallback
(non-lowered) kinds run the interpreter loop behind the same seam.

Flags mirrored from the reference (manager.go:55-71): audit-interval (60s),
constraint-violations-limit (20), audit-chunk-size (500),
audit-match-kind-only.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional, Sequence

import numpy as np

from gatekeeper_tpu.apis.constraints import AUDIT_EP, Constraint
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.base import ReviewCfg
from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.utils.unstructured import gvk_of


@dataclass
class AuditConfig:
    interval_s: float = 60.0
    violations_limit: int = 20  # --constraint-violations-limit
    chunk_size: int = 500  # --audit-chunk-size
    match_kind_only: bool = False  # --audit-match-kind-only
    from_cache: bool = False  # --audit-from-cache
    # sweep schedule (--pipeline): 'auto' takes the staged host pipeline
    # (pipeline/executor.py — flatten, dispatch, collect and fold on
    # their own threads with bounded queues, so chunk K's flatten
    # overlaps chunk K-1's collect/fold) when the host has >1 effective
    # core; 'on'/'off' force it; 'differential' runs BOTH schedules and
    # asserts bit-identical output (totals, kept order, messages)
    pipeline: str = "auto"
    # threads in the flatten stage; 0 = auto (2 on hosts with >=4
    # effective cores, else 1).  The C columnizer already shards one
    # chunk over an internal pthread pool with the GIL released, so
    # cross-chunk workers mainly overlap the GIL-held assembly slices;
    # >1 worker makes vocab-intern ORDER depend on thread timing (ids
    # stay self-consistent and verdicts/messages identical — the warm
    # pass freezes the vocab before timed sweeps anyway) and emission
    # order stays canonical either way (the executor restores input
    # order).
    pipeline_flatten_workers: int = 0
    # bound of each inter-stage queue (chunks buffered between stages);
    # the collect stage's input bound is submit_window, not this
    pipeline_queue_cap: int = 2
    # exact totals = reference parity: totalViolations counts every violation
    # *result* (a pod with 2 privileged containers contributes 2), which
    # requires rendering every hit through the interpreter.  False counts
    # violating objects from the device grid — faster on violation-dense
    # clusters, at the cost of undercounting multi-violation objects.
    exact_totals: bool = True
    # how many chunks may be in flight on the device before the oldest is
    # collected.  Tunneled TPU backends degrade host->device bandwidth
    # ~40x after the process's FIRST device->host fetch (measured on
    # axon: 1.6GB/s -> ~40MB/s, permanent), so the sweep submits as many
    # chunks as possible — every upload at full bandwidth — before the
    # first collect.  Results are tiny (top-k + packed bits), inputs are
    # freed as the device drains the queue, so a deep window costs
    # little HBM.
    submit_window: int = 64
    # resilience (resilience/policy.py): a chunk whose submit/collect/fold
    # raises is re-submitted up to chunk_retries times, then SKIPPED —
    # the run finishes with partial results and an explicit `incomplete`
    # marker instead of aborting the pass.  Stage workers in the
    # pipelined schedule restart and re-run their item up to
    # pipeline_stage_retries times; past that the executor aborts and
    # the sweep degrades to the serial schedule mid-pass.
    chunk_retries: int = 1
    pipeline_stage_retries: int = 1
    # sweep input (--audit-source): 'relist' pages the cluster through
    # the lister every pass (the reference shape); 'snapshot' audits the
    # resident columnar snapshot (gatekeeper_tpu/snapshot/) — a full
    # pass evaluates resident columns with zero list/flatten cost, and
    # `audit_tick` evaluates only the watch-dirtied row set (O(churn)).
    # Snapshot mode ignores match_kind_only (the router already scopes
    # evaluation to kinds some template can match).
    audit_source: str = "relist"
    # snapshot mode: every Nth interval runs the full-resync
    # differential (fresh relist + re-flatten asserted bit-identical to
    # the resident snapshot) instead of an incremental tick; 0 = never
    resync_every: int = 10
    # rotate the resync differential over 1/K of the RowIdMap keyspace
    # per resync interval (--snapshot-resync-rotate): each rotated
    # resync re-flattens only its deterministic key-hash slice, so the
    # bit-identity proof amortizes to ~1/K cost per interval and K
    # consecutive resyncs cover every row (a one-shot 40k-object
    # re-flatten on the 1-core host is ~19s; rotated at K=8 each
    # interval pays ~1/8 of that).  Rotated resyncs prove the STORE
    # (columns + vocab + membership); the cluster-global verdict
    # differential (top-k is a whole-cluster property) runs only when
    # rotation is off.  0/1 = off (the one-shot full differential)
    resync_rotate: int = 0
    # data-parallel chunk sharding (--shard-chunks): pack K consecutive
    # same-group chunks into ONE mesh-wide dispatch — the object axis
    # already shards over the mesh's 'data' axis (parallel/sharded.py
    # shard_batch_arrays), so with K ~= n_devices each chip evaluates
    # ~chunk_size objects while the per-dispatch fixed costs (masks,
    # wire pack, device_put commands, jit call) amortize K-fold.
    # Verdicts are bit-identical to unsharded: objects keep their
    # canonical listed order inside the packed chunk, so totals,
    # top-k kept selection and rendered messages are unchanged
    # (asserted by the simulated-mesh parity tests).  0/1 = off
    # (every chunk dispatches alone — the single-chip reference path)
    shard_chunks: int = 0
    # expansion generator stage (--audit-expand): generator objects
    # (Deployment etc.) listed by the sweep expand through the batched
    # mutlane.ExpansionStage and their resultants (implied Pods, with
    # Source=Generated mutation applied) are audited at sweep scale with
    # the template's enforcementAction override — policies on the
    # generated GVK see violations BEFORE any Pod exists (shift-left).
    # Generated objects bypass match_kind_only (their kinds come from
    # the templates, not the lister).
    expand_generated: bool = False


@dataclass
class Violation:
    constraint: Constraint
    message: str
    enforcement_action: str
    group: str
    version: str
    kind: str
    name: str
    namespace: str
    details: Any = None


@dataclass
class AuditRun:
    timestamp: str = ""
    total_objects: int = 0
    total_violations: dict = field(default_factory=dict)  # (kind,name) -> int
    kept: dict = field(default_factory=dict)  # (kind,name) -> list[Violation]
    duration_s: float = 0.0
    # partial-result marker: True when any chunk was dropped after
    # exhausting its retries or the lister died mid-sweep — totals/kept
    # then UNDERCOUNT and downstream consumers (status writeback, export,
    # `--once` output) see the run flagged instead of silently short
    incomplete: bool = False
    failed_chunks: int = 0
    retried_chunks: int = 0
    # effective ingest/dispatch geometry of the pass, recorded so
    # SWEEP1M history entries and `--once` output are self-describing
    # (no cross-referencing of flags to know what a run measured)
    flatten_workers: int = 0
    n_devices: int = 0
    shard_chunks: int = 0


def violation_rows(bits_or_hits, ci: int, n: int) -> np.ndarray:
    """Violating object indices of local constraint ``ci`` from either
    collect shape: bit-packed verdict rows (the masks lane) or a
    device-reduced ``HitRows`` coordinate list (``--collect=reduced``;
    duck-typed so this module stays jax-free for the sidecar control
    plane).  The single accessor every exact/snapshot fold shares — both
    collect lanes are bit-identical through it by construction."""
    rows = getattr(bits_or_hits, "rows", None)
    if rows is not None:
        return rows(ci)
    return np.nonzero(np.unpackbits(bits_or_hits[ci], count=n))[0]


def _sweep_ready(pending) -> bool:
    """True when a submitted sweep's result needs no further wait
    (non-blocking).  Empty submits ({}) are always ready; RPC futures
    (RemoteEvaluator) answer via ``done()``; local sweeps via the jax
    arrays' ``is_ready()``."""
    done = getattr(pending, "done", None)
    if callable(done):  # grpc future from RemoteEvaluator.sweep_submit
        try:
            return bool(done())
        except Exception:
            return True  # the error surfaces at sweep_collect
    res = getattr(pending, "result", None)
    if res is None:
        return True
    arrs = res if isinstance(res, tuple) else (res,)
    try:
        return all(a.is_ready() for a in arrs)
    except AttributeError:  # test evaluators returning plain numpy
        return True


class AuditManager:
    """One audit plane instance (the reference's audit Deployment pod)."""

    def __init__(
        self,
        client: Client,
        lister: Callable[[], Iterable[dict]],
        config: Optional[AuditConfig] = None,
        evaluator=None,  # parallel.sharded.ShardedEvaluator (optional)
        status_writer: Optional[Callable] = None,
        export_system=None,
        event_sink: Optional[Callable] = None,
        log_violations: bool = False,
        metrics=None,  # metrics.registry.MetricsRegistry (optional)
        snapshot=None,  # snapshot.ClusterSnapshot (audit_source=snapshot)
        expansion_system=None,  # expansion.ExpansionSystem (expand stage)
        spiller=None,  # snapshot.SnapshotSpiller (--snapshot-spill)
        cluster: str = "",  # fleet scope: labels staleness gauges
        residency=None,  # snapshot.DeviceResidency (resident tick lane)
    ):
        self.client = client
        self.lister = lister
        self.config = config or AuditConfig()
        self.evaluator = evaluator
        self.status_writer = status_writer
        self.export_system = export_system
        self.event_sink = event_sink
        self.log_violations = log_violations
        self.metrics = metrics
        self.snapshot = snapshot
        # fleet mode (fleet/evaluator.py): a non-empty cluster id adds
        # a {cluster}-labeled copy of the last-run gauges so the
        # per-cluster audit-staleness SLO objectives (observability/
        # slo.py per_cluster_objectives) can age each cluster's audit
        # independently off one shared registry
        self.cluster = cluster
        # device-resident snapshot lane (snapshot/device_residency.py):
        # when set, _snapshot_eval prefers resident chunks (gather-index
        # H2D only) and falls back to host columns per group whenever
        # the residency declines (no device, extdata, eviction)
        self.residency = residency
        self.expansion_system = expansion_system
        # expansion generator stage state: the batched stage (lazy), the
        # per-sweep generator-object tee, the Namespace inventory the
        # expand needs, and — snapshot mode — per-parent-gid generated
        # verdicts so the stage stays O(churn) like the base rows
        self._expansion_stage = None
        self._gen_buf: Optional[list] = None
        self._gen_ns: dict = {}
        self._gen_kinds: set = set()
        self._gen_verdicts: dict = {}
        # snapshot spill writer (snapshot/persist.py): a clean resync
        # requests a background spill, run_forever's exit flushes a
        # final one (the drain guarantee); None = persistence off
        self.spiller = spiller
        if spiller is not None:
            self.attach_spiller(spiller)
        # human-readable first difference of the last resync differential
        # (None = bit-identical), for tests/ops introspection
        self.last_resync_diff: Optional[str] = None
        # rotated-resync rotor position (wraps mod resync_rotate)
        self._resync_phase = 0
        self._stop = threading.Event()
        # per-phase seconds for the host-side fold/render of device sweeps
        # (the evaluator tracks its own flatten/masks/wire/dispatch/collect)
        self.perf: dict = {}
        # per-stage breakdown of the last pipelined sweep (JSON-ready dict
        # from pipeline.executor.PipelineRun.summary + device-idle proxy);
        # None when the last sweep ran the serial schedule
        self.pipe_stats: Optional[dict] = None

    # --- spill persistence (snapshot/persist.py) -------------------------
    def attach_spiller(self, spiller) -> None:
        """Wire a SnapshotSpiller: the manager feeds it the expansion
        stage's generated verdicts (they ride the spill's aux section so
        a warm boot's totals include them without re-expanding clean
        parents) and flushes it at drain."""
        self.spiller = spiller
        spiller.aux_fn = lambda: {
            "gen_verdicts": dict(self._gen_verdicts)}

    def restore_spill_aux(self, aux: dict) -> None:
        """Adopt a loaded spill's aux section (persist.load's 'aux')."""
        gen = aux.get("gen_verdicts")
        if gen:
            self._gen_verdicts = dict(gen)

    # --- loop (reference: auditManagerLoop, manager.go:831) -------------
    def run_forever(self):
        if self._snapshot_mode():
            # initial full pass builds the snapshot and evaluates every
            # row; steady state is incremental ticks over the dirty set,
            # with the full-resync differential every resync_every-th
            # interval proving the snapshot still equals a fresh relist.
            # A spill-loaded snapshot (persist.load) boots WARM: rows
            # are clean with persisted verdicts, so the first pass is an
            # incremental tick — zero relist, zero flatten, zero
            # re-evaluation of clean rows
            if getattr(self.snapshot, "warm_loaded", False):
                self.audit_tick()
            else:
                self.audit()
            n = 0
            every = max(0, getattr(self.config, "resync_every", 0))
            while not self._stop.wait(self.config.interval_s):
                n += 1
                if every and n % every == 0 and \
                        not self._resync_deferred():
                    self.audit_resync()
                else:
                    self.audit_tick()
            if self.spiller is not None:
                # drain flush: a clean SIGTERM never loses the resident
                # state it just paid to build (synchronous — the process
                # is leaving anyway and the DrainCoordinator budget
                # covers it)
                self.spiller.spill_now()
            return
        while not self._stop.wait(self.config.interval_s):
            self.audit()

    def stop(self):
        self._stop.set()

    # --- one sweep (reference: audit(), manager.go:258) -----------------
    def audit(self) -> AuditRun:
        """One sweep under its root span: the per-stage busy/wall/idle
        numbers the ROADMAP says to read from the bench JSON are ALSO
        recorded as attributes here, so a trace timeline carries them."""
        from gatekeeper_tpu.observability import tracing

        with tracing.span("audit.sweep") as sp:
            if self._snapshot_mode():
                sp.set_attribute("source", "snapshot")
                run = self._audit_snapshot_impl(full=True)
            else:
                run = self._audit_impl()
            sp.set_attribute("objects", run.total_objects)
            sp.set_attribute("duration_s", round(run.duration_s, 3))
            sp.set_attribute("violations",
                             sum(run.total_violations.values()))
            # effective ingest/dispatch geometry — the trace timeline
            # names what it measured without cross-referencing flags
            sp.set_attribute("flatten_workers", run.flatten_workers)
            sp.set_attribute("n_devices", run.n_devices)
            sp.set_attribute("shard_chunks", run.shard_chunks)
            if run.incomplete:
                sp.set_attribute("incomplete", True)
            if self.pipe_stats:
                sp.set_attribute("wall_s", self.pipe_stats.get("wall_s"))
                sp.set_attribute(
                    "stage_busy_sum_s",
                    self.pipe_stats.get("stage_busy_sum_s"))
                sp.set_attribute(
                    "device_idle_fraction",
                    self.pipe_stats.get("device_idle_fraction"))
                sp.set_attribute(
                    "overlap_ratio", self.pipe_stats.get("overlap_ratio"))
            return run

    def _annotate_run(self, run: AuditRun) -> None:
        """Stamp the effective ingest/dispatch geometry onto the run."""
        run.flatten_workers = int(
            getattr(self.evaluator, "flatten_workers", 0) or 0)
        mesh = getattr(self.evaluator, "mesh", None)
        run.n_devices = int(mesh.size) if mesh is not None else 0
        run.shard_chunks = max(
            0, int(getattr(self.config, "shard_chunks", 0) or 0))

    def _audit_impl(self) -> AuditRun:
        t0 = time.time()
        run = AuditRun(timestamp=_now_rfc3339())
        self._annotate_run(run)
        constraints = [
            c for c in self.client.constraints()
            if c.actions_for(AUDIT_EP)
        ]
        if self.export_system is not None:
            self.export_system.publish_audit_started(run.timestamp)
        if not constraints:
            run.duration_s = time.time() - t0
            self._finish(run)
            return run

        kind_filter = None
        if self.config.match_kind_only:
            kind_filter = self._kinds_of(constraints)

        gen_stage = self._gen_stage()
        self._gen_reset(gen_stage is not None)

        limit = self.config.violations_limit
        kept: dict = {(c.kind, c.name): [] for c in constraints}
        totals: dict = {(c.kind, c.name): 0 for c in constraints}

        from gatekeeper_tpu.pipeline import resolve_schedule

        batch_driver = next(
            (d for d in self.client.drivers if hasattr(d, "query_batch")),
            None,
        )
        device = self.evaluator is not None and batch_driver is not None
        use_router = (
            device
            and getattr(self.evaluator, "renders", False) is False
        )
        # staged-pipeline eligibility: a LOCAL evaluator exposing the
        # split flatten/dispatch stages.  The sidecar lane (renders=True,
        # grpc futures) and the no-evaluator interpreter lane stay serial.
        device_capable = (
            use_router
            and hasattr(self.evaluator, "sweep_flatten")
            and hasattr(self.evaluator, "sweep_dispatch")
        )
        schedule = resolve_schedule(
            getattr(self.config, "pipeline", "auto"), device_capable)
        self.pipe_stats = None
        self.perf["pipelined"] = 1.0 if schedule == "pipelined" else 0.0

        counter = [0]
        if schedule == "differential":
            # serial is the reference schedule; the pipelined pass must
            # reproduce it bit-for-bit (totals, kept order, messages)
            self._sweep_serial(constraints, kind_filter, use_router,
                               device, kept, totals, limit, counter, run)
            kept_p: dict = {k: [] for k in kept}
            totals_p: dict = {k: 0 for k in totals}
            self._sweep_pipelined(constraints, kind_filter, use_router,
                                  kept_p, totals_p, limit, [0], run)
            diff = self._schedules_differ(kept, totals, kept_p, totals_p)
            if diff:
                raise RuntimeError(
                    f"pipeline differential mismatch: {diff}")
            self.perf["pipeline_differential_ok"] = 1.0
        elif schedule == "pipelined":
            try:
                self._sweep_pipelined(constraints, kind_filter, use_router,
                                      kept, totals, limit, counter, run)
            except Exception as e:
                # graceful degradation: a pipeline whose stage kept
                # crashing past its restart budget aborts cleanly — the
                # sweep reruns on the one-thread serial schedule instead
                # of losing the pass (chunks re-list from the source, so
                # nothing is dropped)
                from gatekeeper_tpu.utils.logging import log_event

                log_event("warning",
                          "pipelined sweep failed; degrading to the "
                          "serial schedule",
                          event_type="audit_degraded", error=str(e))
                if self.metrics is not None:
                    from gatekeeper_tpu.metrics import registry as M

                    self.metrics.inc_counter(
                        M.RESILIENCE_DEGRADED,
                        {"component": "audit", "to": "serial"})
                for k in kept:
                    kept[k] = []
                for k in totals:
                    totals[k] = 0
                counter[0] = 0
                self.pipe_stats = None
                self.perf["pipelined"] = 0.0
                self.perf["degraded_to_serial"] = (
                    self.perf.get("degraded_to_serial", 0.0) + 1.0)
                self._sweep_serial(constraints, kind_filter, use_router,
                                   device, kept, totals, limit, counter,
                                   run)
        else:
            self._sweep_serial(constraints, kind_filter, use_router,
                               device, kept, totals, limit, counter, run)
        run.total_objects = counter[0]

        if gen_stage is not None and self._gen_buf:
            # the generator stage: expanded resultants audit AFTER the
            # base pass so base kept-ordering stays schedule-identical
            self._sweep_generated(gen_stage, self._gen_buf, constraints,
                                  kept, totals, limit, run)

        run.total_violations = totals
        run.kept = kept
        run.duration_s = time.time() - t0
        self._write_statuses(run, constraints)
        self._publish_metrics(run)
        self._finish(run)
        return run

    # --- snapshot lane (gatekeeper_tpu/snapshot/) -------------------------
    def _snapshot_mode(self) -> bool:
        return (getattr(self.config, "audit_source", "relist")
                == "snapshot" and self.snapshot is not None)

    def audit_tick(self) -> AuditRun:
        """Incremental snapshot audit: evaluate ONLY the dirty row set
        (rows the watch patched since the last evaluation) — O(churn),
        not O(cluster).  Cluster-wide totals/kept come from the
        persistent per-row verdict store (clean rows keep their last
        results)."""
        from gatekeeper_tpu.observability import tracing

        with tracing.span("audit.tick") as sp:
            run = self._audit_snapshot_impl(full=False)
            sp.set_attribute("objects", run.total_objects)
            sp.set_attribute("duration_s", round(run.duration_s, 3))
            gc = getattr(getattr(self.evaluator, "driver", None),
                         "gen_coord", None)
            if gc is not None:
                # which template generation this tick evaluated under —
                # a tick spanning a swap shows the post-swap id and its
                # rows re-chunked (snapshot.rechunk), never a relist
                sp.set_attribute("generation", gc.gen_id)
            if run.incomplete:
                sp.set_attribute("incomplete", True)
            return run

    def _snapshot_ready(self, constraints) -> bool:
        """Adopt the constraint set, rebuild if stale, apply queued watch
        events.  Returns True when a rebuild happened."""
        snap = self.snapshot
        rebuilt = False
        rechunks = getattr(snap, "rechunk_count", 0)
        if snap.set_constraints(constraints):
            from gatekeeper_tpu.utils.logging import log_event

            n = snap.rebuild(self.lister)
            rebuilt = True
            # row ids may outlive a rebuild but the verdict store was
            # reset — generated verdicts reset with it (the full pass
            # recomputes them for every row)
            self._gen_verdicts.clear()
            log_event("info", "snapshot rebuilt",
                      event_type="snapshot_rebuilt", rows=n,
                      generation=snap.generation)
        elif getattr(snap, "rechunk_count", 0) != rechunks:
            from gatekeeper_tpu.utils.logging import log_event

            # a template/constraint (generation) change was absorbed by
            # re-chunking resident rows — zero relist; the verdict store
            # reset with the plan, so generated verdicts reset too and
            # the all-dirty tick re-derives everything
            self._gen_verdicts.clear()
            log_event("info", "snapshot rechunked (no relist)",
                      event_type="snapshot_rechunked",
                      rows=snap.live_count(),
                      generation=snap.generation)
        snap.pump()
        return rebuilt

    def _audit_snapshot_impl(self, full: bool) -> AuditRun:
        t0 = time.time()
        run = AuditRun(timestamp=_now_rfc3339())
        self._annotate_run(run)
        constraints = [
            c for c in self.client.constraints()
            if c.actions_for(AUDIT_EP)
        ]
        if self.export_system is not None:
            self.export_system.publish_audit_started(run.timestamp)
        if not constraints:
            run.duration_s = time.time() - t0
            self._finish(run)
            return run
        snap = self.snapshot
        self._snapshot_ready(constraints)
        rows = snap.all_rows() if full else snap.dirty_rows()
        self.perf["snapshot_rows_evaluated"] = (
            self.perf.get("snapshot_rows_evaluated", 0.0)
            + sum(len(v) for v in rows.values()))
        # tick H2D meter: bytes this tick shipped host->device, summed
        # over the resident lane's honest counter (gather indices, cache
        # misses, residency patches) and the host lane's wire pack — a
        # warm clean-rows resident tick reads ZERO
        ev = self.evaluator
        h2d0 = (ev.perf.get("resident_h2d_bytes", 0.0)
                + ev.perf.get("wire_bytes", 0.0)) if ev is not None else 0.0
        self._snapshot_eval(rows, run)
        # generator stage rides the same dirty set: only (re)evaluated
        # parents re-expand, clean parents keep their generated verdicts
        self._snapshot_generated(rows, constraints, run)
        run.total_objects = snap.live_count()
        totals, kept = self._snapshot_collect(constraints)
        run.total_violations = totals
        run.kept = kept
        run.duration_s = time.time() - t0
        if ev is not None:
            tick_h2d = (ev.perf.get("resident_h2d_bytes", 0.0)
                        + ev.perf.get("wire_bytes", 0.0)) - h2d0
            self.perf["tick_h2d_bytes"] = tick_h2d
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                labels = {"cluster": self.cluster} if self.cluster \
                    else None
                self.metrics.set_gauge(M.TICK_H2D_BYTES,
                                       float(tick_h2d), labels)
        snap.publish_metrics()
        self._write_statuses(run, constraints)
        self._publish_metrics(run)
        self._finish(run)
        return run

    def _snapshot_eval(self, rows_by_store, run) -> None:
        """Evaluate snapshot rows group by group: resident columns slice
        straight into device sweep chunks (zero flatten), non-lowered
        kinds run their drivers' exact lane over the same rows; each
        evaluated row's verdict-store entries are REPLACED.  A chunk that
        exhausts its retries keeps its rows dirty and its previous
        (stale-but-complete) entries, and flags the run incomplete."""
        from collections import deque

        snap = self.snapshot
        ev = self.evaluator
        retries = max(0, getattr(self.config, "chunk_retries", 1))
        # chunk sharding (see AuditConfig.shard_chunks): snapshot rows
        # slice into K-chunk-wide dispatches so the mesh data axis sees
        # K x chunk_size objects per submit; verdict-store totals/kept
        # are per-row and chunk-split-independent, so this is purely a
        # dispatch-geometry change
        shard_k = max(1, int(getattr(self.config, "shard_chunks", 0) or 1))
        chunk_size = max(1, self.config.chunk_size) * shard_k
        max_inflight = max(1, self.config.submit_window)
        from gatekeeper_tpu.observability import tracing

        for store, rowlist in rows_by_store.items():
            cons_g = store.cons
            # resident lane: sync the device mirror ONCE per store per
            # tick (scatter-patch for dirty rows, nothing when clean);
            # None means this group serves host columns this tick
            rg = None
            if self.residency is not None and ev is not None \
                    and store.lowered:
                rg = self.residency.prepare(store)
            window: deque = deque()

            def submit_chunk(gids, positions, objects, _rg=rg):
                if _rg is not None:
                    flat = ev.sweep_flatten_resident(
                        _rg, positions, return_bits=True)
                    if flat is not None:
                        return ev.sweep_dispatch(flat)
                    # generation swapped mid-tick: host path handles it
                batch = store.slice_rows(positions,
                                         pad_n=ev._pad(len(positions)))
                flat = ev.sweep_flatten_from_batch(
                    cons_g, batch, objects, return_bits=True,
                    alias=store.alias)
                return ev.sweep_dispatch(flat)

            def chunk_failed(exc):
                run.failed_chunks += 1
                run.incomplete = True
                from gatekeeper_tpu.utils.logging import log_event

                log_event("warning",
                          "snapshot audit chunk dropped after exhausting "
                          "retries (rows stay dirty; previous verdicts "
                          "kept)", event_type="audit_chunk_failed",
                          phase="snapshot", error=str(exc))
                if self.metrics is not None:
                    from gatekeeper_tpu.metrics import registry as M

                    self.metrics.inc_counter(M.RESILIENCE_CHUNKS_FAILED)

            def fold_oldest():
                pending, gids, positions, objects, chunk_i = \
                    window.popleft()
                with tracing.span("audit.chunk.collect_fold",
                                  chunk=chunk_i, objects=len(gids)):
                    last = None
                    swept = None
                    for attempt in range(retries + 1):
                        try:
                            if attempt > 0:
                                run.retried_chunks += 1
                                pending = submit_chunk(gids, positions,
                                                       objects)
                            swept = ev.sweep_collect(pending)
                            break
                        except Exception as e:  # noqa: PERF203
                            last = e
                    else:
                        chunk_failed(last)
                        return
                    try:
                        t0 = time.perf_counter()
                        self._fold_snapshot_chunk(swept, cons_g, gids,
                                                  objects)
                        snap.mark_clean(gids)
                        self.perf["fold_render"] = (
                            self.perf.get("fold_render", 0.0)
                            + time.perf_counter() - t0)
                    except Exception as e:
                        chunk_failed(e)

            for ci, i in enumerate(range(0, len(rowlist), chunk_size)):
                chunk = rowlist[i: i + chunk_size]
                gids = [g for g, _p in chunk]
                positions = [p for _g, p in chunk]
                objects = [store.row_obj(p) for p in positions]
                pending = None
                if store.lowered and ev is not None:
                    with tracing.span("audit.chunk.submit", chunk=ci,
                                      objects=len(gids)):
                        last = None
                        for attempt in range(retries + 1):
                            try:
                                if attempt > 0:
                                    run.retried_chunks += 1
                                pending = submit_chunk(gids, positions,
                                                       objects)
                                break
                            except Exception as e:  # noqa: PERF203
                                last = e
                        else:
                            chunk_failed(last)
                            continue
                window.append((pending, gids, positions, objects, ci))
                while window and (len(window) > max_inflight
                                  or _sweep_ready(window[0][0])):
                    fold_oldest()
            while window:
                fold_oldest()

    # --- fleet seam (gatekeeper_tpu/fleet/evaluator.py) ------------------
    def fold_snapshot_segment(self, swept, cons_g, gids, objects) -> None:
        """Fold ONE cluster's segment of a fleet-packed dispatch into
        this manager's verdict store and mark its rows clean — the
        packed twin of the per-chunk collect+fold in
        :meth:`_snapshot_eval`.  ``swept`` carries segment-rebased hit
        rows (``fleet.evaluator._SegmentHits`` duck-types the bits
        slot), so the fold is bit-identical to an unpacked chunk of the
        same rows: device hits replace verdict-store entries (exact
        mode renders every hit now), non-lowered constraints run the
        drivers' exact lane over the segment's objects."""
        self._fold_snapshot_chunk(swept, cons_g, gids, objects)
        self.snapshot.mark_clean(gids)

    def snapshot_collect(self, constraints) -> tuple:
        """(totals, kept) off the verdict store — the fleet scheduler's
        per-cluster derivation (same path the snapshot tick uses)."""
        return self._snapshot_collect(constraints)

    def _render_fn(self, source=SOURCE_ORIGINAL):
        """(render, review_cache): the exact-engine render for one
        (constraint, object) hit — the same path the relist fold uses,
        so messages/details are bit-identical across audit sources."""
        target = self.client.target
        driver = next(
            (d for d in self.client.drivers if hasattr(d, "query_batch")),
            None,
        )
        cfg = ReviewCfg(enforcement_point=AUDIT_EP)
        cache: dict = {}

        def render(con, obj, cache_key=None):
            self.perf["n_renders"] = self.perf.get("n_renders", 0) + 1
            t0 = time.perf_counter()
            review = cache.get(cache_key) if cache_key is not None \
                else None
            if review is None:
                review = target.handle_review(AugmentedUnstructured(
                    object=obj, source=source))
                if cache_key is not None:
                    cache[cache_key] = review
            if hasattr(driver, "render_query"):
                results = driver.render_query(
                    target.name, con, review, cfg).results
            else:
                results = driver._interp.query(
                    target.name, [con], review, cfg).results
            self._attr_render(con, time.perf_counter() - t0)
            return results

        return render

    @staticmethod
    def _attr_render(con, dt: float) -> None:
        """Exact per-template attribution of one exact-engine render
        (the host-side cost of a device hit) — no apportioning needed,
        the call IS template-scoped."""
        from gatekeeper_tpu.observability import costattr

        attr = costattr.active()
        if attr is not None:
            attr.record(con.kind, costattr.EP_AUDIT,
                        costattr.PHASE_RENDER, dt, rows=1)

    def _fold_snapshot_chunk(self, swept, cons_g, gids, objects) -> None:
        """Replace the verdict-store entries of an evaluated row set:
        device hits from the bit-packed verdict rows (exact-totals mode
        renders every hit now; otherwise messages render lazily at kept
        time), non-lowered constraints via their drivers' exact lane."""
        snap = self.snapshot
        exact = self.config.exact_totals
        for gid in gids:
            snap.verdicts.clear_gid(gid)
        render = self._render_fn()
        k = len(gids)
        if isinstance(swept, dict):
            for kind, (kcons, idx, valid, counts, bits) in swept.items():
                for ci, con in enumerate(kcons):
                    ckey = con.key()
                    hit = violation_rows(bits, ci, k)
                    for oi in hit.tolist():
                        if exact:
                            results = render(con, objects[oi],
                                             cache_key=oi)
                            msgs = tuple(
                                (r.msg,
                                 (r.metadata or {}).get("details"))
                                for r in results)
                            snap.verdicts.set(ckey, gids[oi],
                                              len(results), msgs)
                        else:
                            snap.verdicts.set(ckey, gids[oi], 1, None)
        rest = [c for c in cons_g
                if not isinstance(swept, dict) or c.kind not in swept]
        if rest:
            per_row = self._eval_rows_via_drivers(rest, objects)
            for oi, per_con in per_row.items():
                for ckey, results in per_con.items():
                    snap.verdicts.set(ckey, gids[oi], len(results),
                                      tuple(results))

    def _eval_rows_via_drivers(self, constraints, objects,
                               source=SOURCE_ORIGINAL) -> dict:
        """Exact-lane evaluation with per-row capture:
        {oi: {con_key: [(msg, details), ...]}} — the snapshot's analog of
        :meth:`_eval_via_drivers` (same drivers, same matcher prefilter,
        results keyed per row for the verdict store)."""
        out: dict = {}
        if not constraints:
            return out
        target = self.client.target
        reviews = [
            target.handle_review(
                AugmentedUnstructured(object=o, source=source))
            for o in objects
        ]
        wanted = {c.key() for c in constraints}
        by_driver: dict = {}
        for con in constraints:
            d = self.client._template_driver.get(con.kind)
            if d is None:
                continue
            by_driver.setdefault(id(d), (d, []))[1].append(con)
        cfg = ReviewCfg(enforcement_point=AUDIT_EP)
        for d, cons in by_driver.values():
            if hasattr(d, "query_batch"):
                responses = d.query_batch(target.name, cons, reviews, cfg)
                for oi, resp in enumerate(responses):
                    for r in resp.results:
                        ckey = (r.constraint.get("kind", ""),
                                (r.constraint.get("metadata") or {})
                                .get("name", ""))
                        if ckey not in wanted:
                            continue
                        out.setdefault(oi, {}).setdefault(
                            ckey, []).append((r.msg, r.details))
                continue
            for oi, review in enumerate(reviews):
                for con in cons:
                    if not target.to_matcher(con.match).match(review):
                        continue
                    qr = d.query(target.name, [con], review, cfg)
                    if qr.results:
                        out.setdefault(oi, {}).setdefault(
                            con.key(), []).extend(
                            (r.msg, r.details) for r in qr.results)
        return out

    def _snapshot_collect(self, constraints) -> tuple:
        """(totals, kept) derived from the verdict store: totals sum
        every row's contribution; kept takes the first ``limit`` rows in
        stable row-id order (messages render lazily on first derivation
        and are cached back into the store)."""
        snap = self.snapshot
        limit = self.config.violations_limit
        totals = {c.key(): 0 for c in constraints}
        kept: dict = {c.key(): [] for c in constraints}
        render = self._render_fn()
        for con in constraints:
            ckey = con.key()
            for gid, count, msgs in snap.verdicts.rows(ckey):
                totals[ckey] += count
                if len(kept[ckey]) >= limit:
                    continue
                obj = snap.obj_of(gid)
                if msgs is None:
                    results = render(con, obj, cache_key=gid)
                    msgs = tuple(
                        (r.msg, (r.metadata or {}).get("details"))
                        for r in results)
                    snap.verdicts.set_msgs(ckey, gid, msgs)
                for msg, details in msgs:
                    if len(kept[ckey]) < limit:
                        kept[ckey].append(
                            self._violation(con, obj, msg, details))
        # generated resultants (expansion generator stage): per-parent
        # entries recomputed whenever the parent row was (re)evaluated,
        # clean parents keep their last generated verdicts — the same
        # O(churn) contract the base rows have
        dead = []
        for gid, per_con in self._gen_verdicts.items():
            if snap.obj_of(gid) is None:
                dead.append(gid)  # parent deleted since the tick
                continue
            for ckey, (count, violations) in per_con.items():
                if ckey not in totals:
                    continue
                totals[ckey] += count
                for v in violations:
                    if len(kept[ckey]) < limit:
                        kept[ckey].append(v)
        for gid in dead:
            self._gen_verdicts.pop(gid, None)
        return totals, kept

    def _eval_objects_capture(self, constraints, objects, source) -> tuple:
        """({oi: {con_key: [(msg, details)]}}, lowered_kinds) — evaluate
        arbitrary objects with per-object capture: device grid + exact
        render for lowered kinds, driver exact lane for the rest.  The
        expansion stage's evaluator for generated resultants."""
        import numpy as np

        out: dict = {}
        swept: dict = {}
        ev = self.evaluator
        device = (ev is not None
                  and getattr(ev, "renders", False) is False
                  and hasattr(ev, "sweep_flatten"))
        if device and objects:
            flat = ev.sweep_flatten(constraints, objects,
                                    return_bits=True, source=source)
            if flat:
                swept = ev.sweep_collect(ev.sweep_dispatch(flat))
        render = self._render_fn(source=source)
        k = len(objects)
        if isinstance(swept, dict):
            for _kind, (kcons, idx, valid, counts, bits) in swept.items():
                for ci, con in enumerate(kcons):
                    hit = violation_rows(bits, ci, k)
                    for oi in hit.tolist():
                        results = render(con, objects[oi], cache_key=oi)
                        out.setdefault(oi, {}).setdefault(
                            con.key(), []).extend(
                            (r.msg, (r.metadata or {}).get("details"))
                            for r in results)
        rest = [c for c in constraints if c.kind not in swept]
        if rest:
            for oi, per_con in self._eval_rows_via_drivers(
                    rest, objects, source=source).items():
                for ckey, results in per_con.items():
                    out.setdefault(oi, {}).setdefault(
                        ckey, []).extend(results)
        return out, set(swept.keys()) if isinstance(swept, dict) else set()

    def _snapshot_generated(self, rows_by_store, constraints, run) -> None:
        """Recompute the generated-resultant verdicts of every parent row
        that was just (re)evaluated: expand through the batched stage,
        evaluate resultants with Source=Generated, store per parent gid.
        A parent that stopped being a generator (or was deleted) simply
        loses its entry."""
        stage = self._gen_stage()
        if stage is None:
            if self._gen_verdicts:
                self._gen_verdicts.clear()
            return
        from gatekeeper_tpu.match.match import SOURCE_GENERATED
        from gatekeeper_tpu.utils.logging import log_event
        from gatekeeper_tpu.utils.unstructured import gvk_of

        snap = self.snapshot
        templates = self.expansion_system.templates()
        gens: list = []
        for store, rowlist in rows_by_store.items():
            for gid, pos in rowlist:
                obj = store.row_obj(pos)
                self._gen_verdicts.pop(gid, None)
                if obj is None:
                    continue
                for t in templates:
                    if t.applies_to(obj):
                        gens.append((gid, obj))
                        break
        if not gens:
            return
        cons_by_key = {c.key(): c for c in constraints}
        exact = self.config.exact_totals
        chunk_size = max(1, self.config.chunk_size)
        for i in range(0, len(gens), chunk_size):
            part = gens[i:i + chunk_size]
            namespaces = []
            for _gid, obj in part:
                ns = (obj.get("metadata") or {}).get("namespace", "") or ""
                namespaces.append(snap.namespace(ns) if ns else None)
            results = stage.expand_batch([o for _g, o in part],
                                         namespaces)
            resultants: list = []  # (parent gid, obj, template, action)
            for (gid, obj), res in zip(part, results):
                if res.error is not None:
                    log_event("warning",
                              "audit expansion failed for a generator "
                              "object", event_type="audit_expand_failed",
                              name=(obj.get("metadata") or {})
                              .get("name", ""), error=str(res.error))
                    continue
                resultants.extend(
                    (gid, r.obj, r.template_name, r.enforcement_action)
                    for r in res.resultants)
            if not resultants:
                continue
            captured, lowered = self._eval_objects_capture(
                constraints, [r[1] for r in resultants],
                SOURCE_GENERATED)
            for oi, (gid, robj, tname, action) in enumerate(resultants):
                for ckey, results in captured.get(oi, {}).items():
                    con = cons_by_key.get(ckey)
                    if con is None:
                        continue
                    # totals parity with the relist generator stage:
                    # non-exact device-lowered kinds count violating
                    # OBJECTS, everything else counts results
                    count = (len(results)
                             if exact or con.kind not in lowered else 1)
                    violations = [
                        self._violation(con, robj, msg, details,
                                        override=(tname, action))
                        for msg, details in results]
                    slot = self._gen_verdicts.setdefault(
                        gid, {}).setdefault(ckey, [0, []])
                    slot[0] += count
                    slot[1].extend(violations)

    def audit_resync(self) -> AuditRun:
        """The periodic full-resync differential (snapshot mode): drain
        the dirty set, then re-list + re-flatten fresh and assert the
        resident snapshot is bit-identical — columns (per-row signatures
        over the same vocab), vocab (the fresh flatten interns nothing
        new), and verdicts (totals + kept against a fresh relist sweep
        through the serial schedule).  Divergence marks the run
        incomplete and invalidates the snapshot: the next sweep
        rebuilds."""
        from gatekeeper_tpu.observability import tracing

        t0 = time.time()
        rotate = max(0, getattr(self.config, "resync_rotate", 0))
        rotor = None
        if rotate > 1:
            rotor = (self._resync_phase % rotate, rotate)
            self._resync_phase = (self._resync_phase + 1) % rotate
        with tracing.span("snapshot.resync") as sp:
            if rotor is not None:
                sp.set_attribute("rotor_phase", rotor[0])
                sp.set_attribute("rotor_k", rotor[1])
            run = self._audit_snapshot_impl(full=False)
            snap = self.snapshot
            diff = snap.resync_differential(self.lister, rotor=rotor)
            if diff is None and rotor is None:
                constraints = [
                    c for c in self.client.constraints()
                    if c.actions_for(AUDIT_EP)
                ]
                kept_f: dict = {c.key(): [] for c in constraints}
                totals_f: dict = {c.key(): 0 for c in constraints}
                fr = AuditRun(timestamp=run.timestamp)
                batch_driver = next(
                    (d for d in self.client.drivers
                     if hasattr(d, "query_batch")), None)
                device = (self.evaluator is not None
                          and batch_driver is not None)
                use_router = (
                    device
                    and getattr(self.evaluator, "renders", False) is False)
                gen_stage = self._gen_stage()
                self._gen_reset(gen_stage is not None)
                self._sweep_serial(constraints, None, use_router, device,
                                   kept_f, totals_f,
                                   self.config.violations_limit, [0], fr)
                if gen_stage is not None and self._gen_buf:
                    # the reference sweep must expand too, or the
                    # differential would flag every generated verdict
                    self._sweep_generated(gen_stage, self._gen_buf,
                                          constraints, kept_f, totals_f,
                                          self.config.violations_limit,
                                          fr)
                self._gen_reset(False)
                diff = self._verdicts_differ_canonical(
                    run.kept, run.total_violations, kept_f, totals_f,
                    self.config.violations_limit)
            self.last_resync_diff = diff
            dt = time.time() - t0
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.set_gauge(M.SNAPSHOT_RESYNC_SECONDS, dt)
            if diff is not None:
                sp.set_attribute("diverged", diff)
                run.incomplete = True
                snap.invalidate()
                from gatekeeper_tpu.utils.logging import log_event

                log_event("warning",
                          "snapshot resync differential diverged; "
                          "snapshot invalidated (next sweep rebuilds)",
                          event_type="snapshot_resync_diverged",
                          difference=diff)
                if self.metrics is not None:
                    from gatekeeper_tpu.metrics import registry as M

                    self.metrics.inc_counter(
                        M.RESILIENCE_DEGRADED,
                        {"component": "snapshot", "to": "rebuild"})
            self.perf["resync_ok"] = 0.0 if diff else 1.0
            # rotated resyncs prove the store slice-by-slice; record the
            # scope so operators can tell a 1/K proof from the full one
            self.perf["resync_scope"] = (1.0 / rotor[1]) if rotor \
                else 1.0
            if diff is None and self.spiller is not None:
                # a just-proven-consistent snapshot is the best state to
                # persist: capture now (under-lock memcpy), write on the
                # spiller's worker — the next tick is untouched
                self.spiller.request()
            return run

    @staticmethod
    def _verdicts_differ_canonical(kept_a, totals_a, kept_b, totals_b,
                                   limit):
        """None when two runs' verdicts agree; kept lists compare as
        CANONICAL (sorted) sets — chunk order legitimately differs
        between the snapshot's row order and a relist's list order, and
        when a constraint's violations exceed the kept limit the top-K
        *selection* under different orders is not canonical (only the
        kept COUNT is compared there; totals stay exact always)."""
        if totals_a != totals_b:
            keys = [k for k in totals_a
                    if totals_a.get(k) != totals_b.get(k)]
            return (f"totals differ for {keys[:3]}: "
                    f"{[totals_a.get(k) for k in keys[:3]]} vs "
                    f"{[totals_b.get(k) for k in keys[:3]]}")
        if set(kept_a) != set(kept_b):
            return "kept constraint sets differ"
        for key in kept_a:
            va = sorted((v.message, v.kind, v.name, v.namespace,
                         v.enforcement_action) for v in kept_a[key])
            vb = sorted((v.message, v.kind, v.name, v.namespace,
                         v.enforcement_action) for v in kept_b[key])
            if len(va) != len(vb):
                return f"kept counts differ for {key}"
            if len(va) < limit and va != vb:
                return f"kept violations differ for {key}"
        return None

    # --- overload brownout (resilience/overload.py) ----------------------
    def _brownout_yield(self) -> None:
        """Brownout level-2 hook: while the webhook admission queue is
        under heavy pressure, the sweep yields the device lane before
        submitting its next chunk (bounded per call — audit slows, never
        stalls).  A no-op without an installed OverloadController, and
        released entirely while a breaching audit-staleness objective
        holds ``audit_yield_release`` (yield_device_lane checks it)."""
        from gatekeeper_tpu.resilience import overload

        waited = overload.yield_device_lane(cluster=self.cluster)
        if waited:
            self.perf["brownout_yield_s"] = (
                self.perf.get("brownout_yield_s", 0.0) + waited)

    def _resync_deferred(self) -> bool:
        """``resync_defer`` degradation action: a breaching
        audit-staleness objective defers the periodic full-resync
        differential (an expensive relist + full re-evaluation) so the
        interval budget goes to catching the dirty set up.  Deferrals
        are counted — a resync deferred is visible, not silent."""
        from gatekeeper_tpu.resilience import overload

        if not overload.degradation_active(overload.RESYNC_DEFER,
                                           self.cluster):
            return False
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.RESILIENCE_DEGRADED,
                {"component": "audit", "to": "resync_defer"})
        return True

    # --- expansion generator stage (mutlane/expand_stage.py) -------------
    def _gen_stage(self):
        """The batched expansion stage, or None when the generator stage
        is off / has nothing to do."""
        if not getattr(self.config, "expand_generated", False):
            return None
        if self.expansion_system is None or \
                not self.expansion_system.templates():
            return None
        if self._expansion_stage is None:
            from gatekeeper_tpu.mutlane import ExpansionStage

            self._expansion_stage = ExpansionStage(
                self.expansion_system, metrics=self.metrics)
        return self._expansion_stage

    def _gen_reset(self, active: bool) -> None:
        """Arm (or disarm) the per-sweep generator tee."""
        self._gen_buf = [] if active else None
        self._gen_ns = {}
        self._gen_kinds = set()
        if active:
            for t in self.expansion_system.templates():
                for entry in t.apply_to:
                    self._gen_kinds.update(entry.get("kinds") or [])

    def _gen_tee(self, obj, kind: str) -> None:
        """Observe one listed object: collect Namespaces (the expand's
        namespace context) and generator objects (some template's
        applyTo covers them).  RawJSON objects only parse when their
        kind pre-qualifies."""
        if self._gen_buf is None:
            return
        if kind == "Namespace":
            name = (obj.get("metadata") or {}).get("name", "") or ""
            if name:
                self._gen_ns[name] = obj
            return
        if kind in self._gen_kinds:
            for t in self.expansion_system.templates():
                if t.applies_to(obj):
                    self._gen_buf.append(obj)
                    break

    def _gen_namespace_of(self, obj):
        ns = (obj.get("metadata") or {}).get("namespace", "") or ""
        return self._gen_ns.get(ns) if ns else None

    def _expand_bases(self, stage, bases) -> tuple:
        """Expand a chunk of generator bases through the batched stage;
        returns (resultants, errors) where each resultant is
        ``(obj, template_name, enforcement_override, ns_obj)``."""
        namespaces = [self._gen_namespace_of(b) for b in bases]
        results = stage.expand_batch(bases, namespaces)
        resultants: list = []
        errors: list = []
        for base, ns_obj, res in zip(bases, namespaces, results):
            if res.error is not None:
                errors.append((base, res.error))
                continue
            for r in res.resultants:
                resultants.append((r.obj, r.template_name,
                                   r.enforcement_action, ns_obj))
        return resultants, errors

    def _sweep_generated(self, stage, bases, constraints, kept, totals,
                         limit, run=None) -> None:
        """The generator stage of a relist sweep: expand the tee'd
        generator objects in chunks, then audit every resultant at sweep
        scale — device grid for lowered kinds (flattened with
        Source=Generated so source-scoped matches hold), driver exact
        lane for the rest — folding into the same kept/totals with the
        template's enforcementAction override and the reference's
        ``[Implied by <template>]`` message prefix."""
        from gatekeeper_tpu.match.match import SOURCE_GENERATED
        from gatekeeper_tpu.observability import tracing
        from gatekeeper_tpu.utils.logging import log_event

        chunk_size = max(1, self.config.chunk_size)
        retries = max(0, getattr(self.config, "chunk_retries", 1))
        device = (self.evaluator is not None
                  and getattr(self.evaluator, "renders", False) is False
                  and hasattr(self.evaluator, "sweep_flatten"))
        router = None
        if device:
            from gatekeeper_tpu.parallel.sharded import make_kind_router

            router = make_kind_router(constraints)

        n_resultants = 0
        with tracing.span("expansion.stage", phase="audit",
                          bases=len(bases)) as sp:
            for i in range(0, len(bases), chunk_size):
                resultants, errors = self._expand_bases(
                    stage, bases[i:i + chunk_size])
                for base, err in errors:
                    # mirrors the webhook's ExpansionError handling:
                    # surfaced, never silently dropped, run keeps going
                    log_event("warning",
                              "audit expansion failed for a generator "
                              "object", event_type="audit_expand_failed",
                              name=(base.get("metadata") or {})
                              .get("name", ""), error=str(err))
                n_resultants += len(resultants)
                self._eval_generated_chunks(
                    resultants, constraints, kept, totals, limit, run,
                    router, device, chunk_size, retries,
                    SOURCE_GENERATED)
            sp.set_attribute("resultants", n_resultants)

    def _eval_generated_chunks(self, resultants, constraints, kept,
                               totals, limit, run, router, device,
                               chunk_size, retries, source) -> None:
        """Evaluate expanded resultants grouped the way the base sweep
        groups objects (kind-bucketed router on the device path)."""
        from gatekeeper_tpu.utils.unstructured import gvk_of

        def fold(objs, cons, overrides):
            last = None
            for attempt in range(retries + 1):
                try:
                    if run is not None and attempt > 0:
                        run.retried_chunks += 1
                    if device:
                        flat = self.evaluator.sweep_flatten(
                            cons, objs,
                            return_bits=self.config.exact_totals,
                            source=source,
                            budget=lambda con: limit - len(
                                kept.get(con.key(), ())))
                        swept = self.evaluator.sweep_collect(
                            self.evaluator.sweep_dispatch(flat))
                        self._process_swept(swept, objs, cons, kept,
                                            totals, limit, source=source,
                                            overrides=overrides)
                    else:
                        self._audit_chunk(objs, cons, kept, totals,
                                          limit, source=source,
                                          overrides=overrides)
                    return
                except Exception as e:  # noqa: PERF203
                    last = e
            if run is not None:
                run.failed_chunks += 1
                run.incomplete = True
            from gatekeeper_tpu.utils.logging import log_event

            log_event("warning",
                      "generated-object audit chunk dropped after "
                      "exhausting retries",
                      event_type="audit_chunk_failed", phase="generated",
                      error=str(last))

        if router is not None:
            bufs: dict = {}
            for obj, tname, action, _ns in resultants:
                _, _, k = gvk_of(obj)
                g = router(k)
                if not g:
                    continue  # no template's match reaches this kind
                bufs.setdefault(g, []).append((obj, tname, action))
            for g, entries in bufs.items():
                cons_g = [c for c in constraints if c.kind in g]
                for j in range(0, len(entries), chunk_size):
                    part = entries[j:j + chunk_size]
                    fold([e[0] for e in part], cons_g,
                         [(e[1], e[2]) for e in part])
        else:
            for j in range(0, len(resultants), chunk_size):
                part = resultants[j:j + chunk_size]
                fold([e[0] for e in part], constraints,
                     [(e[1], e[2]) for e in part])

    # --- sweep chunk source (shared by both schedules) -------------------
    def _chunk_source(self, constraints, kind_filter, use_router, counter):
        """The chunk stream both schedules consume: the canonical
        per-group chunking (:meth:`_chunk_source_impl`), optionally
        coalesced by ``shard_chunks`` — K consecutive chunks of the SAME
        constraint group pack into one mesh-wide dispatch whose object
        axis shards over the mesh's 'data' axis.  Objects keep their
        listed order inside a packed chunk (kept selection order is
        unchanged); only cross-GROUP emission order shifts, which no
        output depends on (groups hold disjoint constraint sets)."""
        src = self._chunk_source_impl(constraints, kind_filter,
                                      use_router, counter)
        k = max(1, int(getattr(self.config, "shard_chunks", 0) or 1))
        if k <= 1:
            yield from src
            return
        pend: dict = {}  # group key -> [objects, cons, chunks packed]
        for objs, cons in src:
            key = tuple((c.kind, c.name) for c in cons)
            buf = pend.get(key)
            if buf is None:
                pend[key] = [list(objs), cons, 1]
                continue
            buf[0].extend(objs)
            buf[2] += 1
            if buf[2] >= k:
                del pend[key]
                yield buf[0], buf[1]
        for objs, cons, _count in pend.values():  # partial tails
            yield objs, cons

    def _chunk_source_impl(self, constraints, kind_filter, use_router,
                           counter):
        """Yield ``(objects, constraint_subset)`` sweep chunks in the ONE
        canonical order both schedules share — the pipelined fold and the
        serial fold therefore see identical chunk sequences, which is what
        makes their outputs bit-identical.

        kind-bucketed routing (device path): objects stream into
        per-kind-group chunks (parallel/sharded.make_kind_router — the
        match-kinds prefilter of manager.go:427-483 applied per
        template), so a Service chunk never flattens/ships/evaluates
        container columns, and objects no template can match skip the
        device entirely.  ``counter[0]`` accumulates listed (post
        kind-filter) objects."""
        if self._gen_buf is not None:
            # one tee per sweep pass: the differential schedule runs
            # this generator twice — a stale buffer would double-expand
            self._gen_buf = []
        if use_router:
            from gatekeeper_tpu.parallel.sharded import make_kind_router
            from gatekeeper_tpu.utils.rawjson import peek_kind

            router = make_kind_router(constraints)
            cons_of_group: dict = {}
            bufs: dict = {}  # group -> pending chunk
            for obj in self.lister():
                k = peek_kind(obj)
                self._gen_tee(obj, k)
                if kind_filter is not None and k not in kind_filter:
                    continue
                counter[0] += 1
                g = router(k)
                if not g:
                    continue  # no template's match reaches this kind
                buf = bufs.setdefault(g, [])
                buf.append(obj)
                if len(buf) >= self.config.chunk_size:
                    cg = cons_of_group.get(g)
                    if cg is None:
                        cg = [c for c in constraints if c.kind in g]
                        cons_of_group[g] = cg
                    self._brownout_yield()
                    yield buf, cg
                    bufs[g] = []
            for g, buf in bufs.items():
                if buf:
                    yield buf, [c for c in constraints if c.kind in g]
        else:
            chunk: list = []
            for obj in self.lister():
                if self._gen_buf is not None or kind_filter is not None:
                    _, _, k = gvk_of(obj)
                    self._gen_tee(obj, k)
                    if kind_filter is not None and k not in kind_filter:
                        continue
                chunk.append(obj)
                counter[0] += 1
                if len(chunk) >= self.config.chunk_size:
                    self._brownout_yield()
                    yield chunk, constraints
                    chunk = []
            if chunk:
                yield chunk, constraints

    # --- serial schedule (eager-poll, the one-core-safe path) ------------
    def _sweep_serial(self, constraints, kind_filter, use_router, device,
                      kept, totals, limit, counter, run=None):
        """Eager-poll pipelined chunking on ONE thread: the host lists +
        flattens + dispatches chunks (jit dispatch is async, so the device
        drains the queue while the host keeps flattening); after each
        submit, any in-flight chunk whose device result IS ALREADY READY
        (non-blocking ``is_ready`` poll) is collected + folded
        immediately.  The host thread therefore never blocks while
        listing continues — by the final drain only the tail chunks are
        still executing, and their wait overlaps their predecessors'
        fold/render.  On a one-core host this beats stage THREADS
        (measured: two GIL-hungry threads thrash — flatten wall-time
        doubled); single-threaded, total time ~= host CPU work with
        device+wire waits hidden.  ``submit_window`` still bounds
        in-flight chunks (host memory + device HBM)."""
        from collections import deque

        window: deque = deque()  # (pending, objects, constraint subset)
        max_inflight = max(1, self.config.submit_window)

        # reduced-collect kept budget: each dispatch tells the device how
        # many kept slots per constraint remain, so drained constraints
        # ship ZERO kept coordinates.  Read at dispatch time the budget
        # is always >= the fold-time remainder (folds only shrink it), so
        # the device selection stays a superset of what the fold keeps —
        # output is bit-identical to the unbudgeted masks fold.
        budget_fn = None
        if device and hasattr(self.evaluator, "sweep_flatten"):
            budget_fn = (lambda con:
                         limit - len(kept.get(con.key(), ())))

        # tunnel-drain waiter: tunneled TPU backends buffer H2D uploads
        # and defer the wire drain until something BLOCKS on a result —
        # is_ready() alone never fires mid-listing, so every chunk's
        # wait piles into the final drain (measured: collect 0.65s of a
        # 2.2s pass with zero eager collects on TPU).  A daemon thread
        # that ONLY calls jax.block_until_ready (a GIL-released C++ wait,
        # zero Python work — a fold-in-thread variant measurably thrashed
        # the one-core GIL) keeps the pipe draining continuously, so the
        # main thread's eager poll finds ready results while it still has
        # flatten work to hide them behind.
        waitq = None
        waiter = None
        if device and getattr(self.evaluator, "renders", False) is False:
            # local ShardedEvaluator only: the sidecar lane's pendings are
            # grpc futures (renders=True) — no jax arrays to drain, and
            # the sidecar-mode control plane is deliberately jax-free
            # (__main__.py "only the local path touches jax")
            import queue

            import jax as _jax

            waitq = queue.Queue()

            def _wait_loop():
                while True:
                    p = waitq.get()
                    if p is None:
                        return
                    try:
                        _jax.block_until_ready(p.result)
                    except Exception:
                        pass  # surfaces at sweep_collect on the main thread

            waiter = threading.Thread(target=_wait_loop, daemon=True,
                                      name="audit-drain-waiter")
            waiter.start()

        retries = max(0, getattr(self.config, "chunk_retries", 1))

        def chunk_failed(exc, phase):
            """Retry budget exhausted: skip the chunk, flag the run."""
            if run is not None:
                run.failed_chunks += 1
                run.incomplete = True
            from gatekeeper_tpu.utils.logging import log_event

            log_event("warning",
                      "audit chunk dropped after exhausting retries",
                      event_type="audit_chunk_failed", phase=phase,
                      error=str(exc))
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.inc_counter(M.RESILIENCE_CHUNKS_FAILED)

        def chunk_retry(exc, phase):
            if run is not None:
                run.retried_chunks += 1
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.inc_counter(M.RESILIENCE_RETRIES,
                                         {"dependency": "audit_chunk"})

        from gatekeeper_tpu.observability import tracing

        def fold_oldest():
            # retry covers the non-mutating phases ONLY (submit/collect):
            # once the fold touches kept/totals a re-run would double
            # count, so a fold failure drops the chunk instead
            pending, objs, cons, chunk_i = window.popleft()
            with tracing.span("audit.chunk.collect_fold", chunk=chunk_i,
                              objects=len(objs)):
                last = None
                swept = None
                for attempt in range(retries + 1):
                    try:
                        if attempt > 0:
                            # a failed collect can't be re-fetched: the whole
                            # chunk re-submits through flatten/dispatch
                            chunk_retry(last, "collect")
                            pending = self.evaluator.sweep_submit(
                                cons, objs,
                                return_bits=self.config.exact_totals,
                                **({"budget": budget_fn}
                                   if budget_fn is not None else {}))
                        swept = self.evaluator.sweep_collect(pending)
                        break
                    except Exception as e:  # noqa: PERF203
                        last = e
                else:
                    chunk_failed(last, "collect")
                    return
                try:
                    t0 = time.perf_counter()
                    self._process_swept(swept, objs, cons, kept, totals,
                                        limit)
                    self.perf["fold_render"] = (
                        self.perf.get("fold_render", 0.0)
                        + time.perf_counter() - t0)
                except Exception as e:
                    chunk_failed(e, "fold")

        def submit(objects, cons, chunk_i):
            if device:
                with tracing.span("audit.chunk.submit", chunk=chunk_i,
                                  objects=len(objects)):
                    last = None
                    for attempt in range(retries + 1):
                        try:
                            if attempt > 0:
                                chunk_retry(last, "submit")
                            pending = self.evaluator.sweep_submit(
                                cons, objects,
                                return_bits=self.config.exact_totals,
                                **({"budget": budget_fn}
                                   if budget_fn is not None else {}))
                            break
                        except Exception as e:  # noqa: PERF203
                            last = e
                    else:
                        chunk_failed(last, "submit")
                        return
                    window.append((pending, objects, cons, chunk_i))
                    if waitq is not None and \
                            getattr(pending, "result", None) is not None:
                        waitq.put(pending)
                while window and (len(window) > max_inflight
                                  or _sweep_ready(window[0][0])):
                    self.perf["n_eager_collects"] = (
                        self.perf.get("n_eager_collects", 0) + 1)
                    fold_oldest()
            else:
                # interpreter lane: evaluate into CHUNK-LOCAL dicts and
                # merge only on success, so a mid-chunk failure (and its
                # retry) can never double count
                with tracing.span("audit.chunk.interp", chunk=chunk_i,
                                  objects=len(objects)):
                    last = None
                    for attempt in range(retries + 1):
                        try:
                            if attempt > 0:
                                chunk_retry(last, "interp")
                            kept_c = {c.key(): [] for c in cons}
                            totals_c = {c.key(): 0 for c in cons}
                            self._audit_chunk(objects, cons, kept_c,
                                              totals_c, limit)
                            for key, n in totals_c.items():
                                totals[key] += n
                            for key, vs in kept_c.items():
                                for v in vs:
                                    if len(kept[key]) < limit:
                                        kept[key].append(v)
                            return
                        except Exception as e:  # noqa: PERF203
                            last = e
                    chunk_failed(last, "interp")

        try:
            src = iter(self._chunk_source(constraints, kind_filter,
                                          use_router, counter))
            chunk_i = -1
            while True:
                try:
                    objs, cons = next(src)
                    chunk_i += 1
                except StopIteration:
                    break
                except Exception as e:
                    # the lister died mid-iteration — a generator cannot
                    # resume, so finish with what was listed and mark the
                    # pass incomplete instead of aborting it
                    if run is not None:
                        run.incomplete = True
                    from gatekeeper_tpu.utils.logging import log_event

                    log_event("warning",
                              "audit lister failed mid-sweep; finishing "
                              "with partial results",
                              event_type="audit_lister_failed",
                              error=str(e))
                    break
                submit(objs, cons, chunk_i)
            while window:  # drain: blocking collect of the tail chunks
                fold_oldest()
        finally:
            # always stop the waiter — a lister/submit/fold exception must
            # not leak a thread blocked on waitq.get() pinning queued
            # device buffers for the life of the process
            if waiter is not None:
                waitq.put(None)
                waiter.join()

    # --- pipelined schedule (staged executor) ----------------------------
    def _sweep_pipelined(self, constraints, kind_filter, use_router,
                         kept, totals, limit, counter, run=None):
        """Staged host pipeline: ``list -> flatten -> dispatch -> collect
        -> fold_render`` with one thread per stage and bounded inter-stage
        queues (pipeline/executor.py).  Chunk K's flatten (GIL-released C
        columnizer) overlaps chunk K-1's collect/fold, so host work hides
        device/wire waits and vice versa; the collect stage's input bound
        is ``submit_window`` (in-flight device chunks: host memory + HBM),
        and the fold stage consumes chunks in submission order so output
        is bit-identical to the serial schedule."""
        from gatekeeper_tpu.pipeline import Stage, StagedPipeline

        import jax as _jax

        ev = self.evaluator
        cfg = self.config
        rb = cfg.exact_totals

        # reduced-collect kept budget (see _sweep_serial): evaluated at
        # DISPATCH on the dispatch stage thread while the fold stage
        # mutates kept — dict/list length reads are atomic under the GIL
        # and budgets only shrink, so a stale read over-ships, never
        # under-ships
        def budget_fn(con):
            return cfg.violations_limit - len(kept.get(con.key(), ()))

        def fl(item):
            objs, cons = item
            return (ev.sweep_flatten(cons, objs, return_bits=rb,
                                     budget=budget_fn), objs, cons)

        def disp(item):
            flat, objs, cons = item
            return ev.sweep_dispatch(flat), objs, cons

        def coll(item):
            pending, objs, cons = item
            res = getattr(pending, "result", None)
            if res is not None:
                # the stage's ONLY blocking wait: device + wire time for
                # the head-of-line chunk (a GIL-released C++ wait) — its
                # busy_s is the run's device-wait measurement
                try:
                    _jax.block_until_ready(res)
                except Exception:
                    pass  # surfaces at sweep_collect below
            return ev.sweep_collect(pending), objs, cons

        def fold(item):
            swept, objs, cons = item
            t0 = time.perf_counter()
            self._process_swept(swept, objs, cons, kept, totals, limit)
            self.perf["fold_render"] = (
                self.perf.get("fold_render", 0.0)
                + time.perf_counter() - t0)
            return None

        from gatekeeper_tpu.pipeline import effective_cpu_count

        fw = cfg.pipeline_flatten_workers
        if fw <= 0:  # auto: a second flatten worker once cores allow it
            fw = 2 if effective_cpu_count() >= 4 else 1
        # crashed-worker restarts: flatten/dispatch/collect re-run their
        # item (idempotent, no run state touched); fold_render mutates
        # kept/totals so it gets NO retry budget — its failure aborts the
        # pipeline and the sweep degrades to the serial schedule
        sr = max(0, getattr(cfg, "pipeline_stage_retries", 1))
        pipe = StagedPipeline([
            Stage("flatten", fl, workers=fw,
                  queue_cap=cfg.pipeline_queue_cap, max_retries=sr),
            Stage("dispatch", disp, queue_cap=cfg.pipeline_queue_cap,
                  max_retries=sr),
            Stage("collect", coll,
                  queue_cap=max(1, cfg.submit_window), max_retries=sr),
            Stage("fold_render", fold, queue_cap=cfg.pipeline_queue_cap),
        ], source_cap=cfg.pipeline_queue_cap)
        pr = pipe.run(self._chunk_source(constraints, kind_filter,
                                         use_router, counter))
        n_retries = sum(s.retries for s in pr.stages)
        if n_retries:
            if run is not None:
                run.retried_chunks += n_retries
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as M

                self.metrics.inc_counter(
                    M.RESILIENCE_RETRIES,
                    {"dependency": "audit_pipeline"},
                    value=float(n_retries))
        stats = pr.summary()
        # device-idle proxy: the collect stage blocks exactly while the
        # device (or wire) is still producing the head-of-line result;
        # the rest of the wall the chip had nothing in flight to finish.
        # An upper bound on device busy (it includes wire drain), hence a
        # LOWER bound on idle-fraction improvements it reports.
        coll_s = pr.stage("collect")
        device_wait = coll_s.busy_s if coll_s is not None else 0.0
        stats["device_wait_s"] = round(device_wait, 3)
        stats["device_idle_fraction"] = (
            round(max(0.0, 1.0 - device_wait / pr.wall_s), 3)
            if pr.wall_s > 0 else 0.0)
        self.pipe_stats = stats
        self.perf["pipe_wall"] = (
            self.perf.get("pipe_wall", 0.0) + pr.wall_s)
        self.perf["pipe_stage_busy_sum"] = (
            self.perf.get("pipe_stage_busy_sum", 0.0)
            + pr.stage_busy_sum())
        self.perf["pipe_device_wait"] = (
            self.perf.get("pipe_device_wait", 0.0) + device_wait)

    @staticmethod
    def _schedules_differ(kept_a, totals_a, kept_b, totals_b):
        """None when two schedules produced bit-identical output, else a
        human-readable first difference (differential mode)."""
        if totals_a != totals_b:
            keys = [k for k in totals_a
                    if totals_a.get(k) != totals_b.get(k)]
            return (f"totals differ for {keys[:3]}: "
                    f"{[totals_a.get(k) for k in keys[:3]]} vs "
                    f"{[totals_b.get(k) for k in keys[:3]]}")
        for key in kept_a:
            va = [(v.message, v.kind, v.name, v.namespace,
                   v.enforcement_action) for v in kept_a[key]]
            vb = [(v.message, v.kind, v.name, v.namespace,
                   v.enforcement_action) for v in kept_b.get(key, [])]
            if va != vb:
                return f"kept violations differ for {key}"
        return None

    def _publish_metrics(self, run: AuditRun) -> None:
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as M

        self.metrics.observe(M.AUDIT_DURATION, run.duration_s)
        now = time.time()
        self.metrics.set_gauge(M.AUDIT_LAST_RUN, now - run.duration_s)
        # end-of-sweep timestamp: the SLO engine's audit-staleness
        # objective ages against this (declared since PR 3, never set)
        self.metrics.set_gauge(M.AUDIT_LAST_RUN_END, now)
        self.metrics.set_gauge(M.AUDIT_LAST_RUN_INCOMPLETE,
                               1.0 if run.incomplete else 0.0)
        if self.cluster:
            # fleet: the per-cluster staleness series the cluster-scoped
            # objectives sample (the unlabeled gauges above keep their
            # process-wide meaning: last sweep of ANY cluster)
            lab = {"cluster": self.cluster}
            self.metrics.set_gauge(M.AUDIT_LAST_RUN,
                                   now - run.duration_s, lab)
            self.metrics.set_gauge(M.AUDIT_LAST_RUN_END, now, lab)
            self.metrics.set_gauge(M.AUDIT_LAST_RUN_INCOMPLETE,
                                   1.0 if run.incomplete else 0.0, lab)
        if not self.pipe_stats:
            return
        for name, s in self.pipe_stats.get("stages", {}).items():
            lab = {"stage": name}
            self.metrics.set_gauge(M.PIPELINE_STAGE_SECONDS,
                                   s["busy_s"], lab)
            self.metrics.set_gauge(M.PIPELINE_STAGE_OCCUPANCY,
                                   s["occupancy"], lab)
            self.metrics.set_gauge(M.PIPELINE_QUEUE_HIGHWATER,
                                   s["queue_highwater"], lab)
        self.metrics.set_gauge(
            M.PIPELINE_DEVICE_IDLE,
            self.pipe_stats.get("device_idle_fraction", 0.0))
        # sweep-level aggregates (previously only in the bench JSON):
        # wall vs summed stage busy is the overlap proof, scrapeable now
        self.metrics.set_gauge(M.PIPELINE_WALL,
                               self.pipe_stats.get("wall_s", 0.0))
        self.metrics.set_gauge(
            M.PIPELINE_STAGE_BUSY_SUM,
            self.pipe_stats.get("stage_busy_sum_s", 0.0))

    def _kinds_of(self, constraints: Sequence[Constraint]) -> set:
        """--audit-match-kind-only prefilter (manager.go:427-483): only valid
        when every constraint names concrete kinds."""
        kinds: set = set()
        for c in constraints:
            entries = (c.match or {}).get("kinds") or []
            if not entries:
                return None  # a constraint matches all kinds: no prefilter
            for e in entries:
                ks = e.get("kinds") or []
                if not ks or "*" in ks:
                    return None
                kinds.update(ks)
        return kinds

    # --- chunk evaluation ------------------------------------------------

    def _audit_chunk(self, objects, constraints, kept, totals, limit,
                     source=SOURCE_ORIGINAL, overrides=None):
        """No-evaluator path: every constraint goes through its template's
        own driver (batched where the driver supports it)."""
        target = self.client.target
        reviews = [
            target.handle_review(
                AugmentedUnstructured(object=o, source=source)
            )
            for o in objects
        ]
        self._eval_via_drivers(constraints, objects, reviews, kept, totals,
                               limit, overrides=overrides)

    def _eval_via_drivers(self, constraints, objects, reviews, kept, totals,
                          limit, overrides=None):
        """Evaluate constraints through their own template's driver: the
        batch path for batch-capable drivers, a matcher-prefiltered per-object
        query loop otherwise.  This is the lane for every constraint the
        device sweep did not cover — non-lowered Rego templates, CEL
        templates (owned by a different driver), and referential templates
        whose inventory tables are inexact for the current data version."""
        if not constraints:
            return
        target = self.client.target
        by_driver: dict[int, tuple] = {}
        for con in constraints:
            d = self.client._template_driver.get(con.kind)
            if d is None:
                continue  # no template: constraint cannot be evaluated
            by_driver.setdefault(id(d), (d, []))[1].append(con)
        for d, cons in by_driver.values():
            if hasattr(d, "query_batch"):
                self._chunk_via_query_batch(d, cons, objects, reviews, kept,
                                            totals, limit,
                                            overrides=overrides)
                continue
            for oi, obj in enumerate(objects):
                review = reviews[oi]
                for con in cons:
                    if not target.to_matcher(con.match).match(review):
                        continue
                    qr = d.query(
                        target.name, [con], review,
                        ReviewCfg(enforcement_point=AUDIT_EP)
                    )
                    key = con.key()
                    totals[key] += len(qr.results)
                    for r in qr.results:
                        if len(kept[key]) < limit:
                            kept[key].append(
                                self._violation(con, obj, r.msg, r.details,
                                                override=(overrides[oi]
                                                          if overrides
                                                          else None)))

    @staticmethod
    def fold_swept(swept, n_objects, render, limit, exact, budget=None):
        """Yield (constraint, total, kept[(oi, msg, details)]) per
        constraint of a device sweep result — the single definition of the
        kept/total fold, shared by the in-process audit and the Evaluate
        sidecar (their parity is asserted in tests/test_sidecar.py).

        ``render(con, oi)`` -> list of exact-engine Results for one hit.
        ``exact``: totals count RESULTS via bit-packed hit rows; otherwise
        totals are the device's violating-object counts and only top-k
        hits render.  ``budget(con)`` -> remaining run-level kept slots for
        a constraint (defaults to ``limit``): in the non-exact path a
        constraint whose run budget is exhausted renders NOTHING for this
        chunk — without it every chunk re-renders up to ``limit`` hits per
        constraint through the exact interpreter only to drop them at the
        run-level cap (~(n_chunks-1)x wasted render work on
        violation-dense corpora)."""
        for kind, (kcons, idx, valid, counts, bits) in swept.items():
            for ci, con in enumerate(kcons):
                kept_list: list = []
                cap = limit if budget is None else min(limit, budget(con))
                if exact and bits is not None:
                    # exact totals count RESULTS: every hit must render
                    # regardless of remaining kept budget
                    hit_idx = violation_rows(bits, ci, n_objects)
                    total = 0
                    for oi in hit_idx.tolist():
                        results = render(con, oi)
                        total += len(results)
                        for r in results:
                            if len(kept_list) < cap:
                                kept_list.append(
                                    (oi, r.msg,
                                     (r.metadata or {}).get("details")))
                else:
                    total = int(counts[ci])
                    for j in range(idx.shape[1]):
                        if not valid[ci, j] or len(kept_list) >= cap:
                            continue
                        oi = int(idx[ci, j])
                        for r in render(con, oi):
                            if len(kept_list) < cap:
                                kept_list.append(
                                    (oi, r.msg,
                                     (r.metadata or {}).get("details")))
                yield con, total, kept_list

    def _process_swept(self, swept, objects, constraints, kept, totals,
                       limit, source=SOURCE_ORIGINAL, overrides=None):
        """Fold one chunk's device results into the run state and run the
        fallback kinds through the exact engine.  ``source``/``overrides``
        carry the expansion generator stage's context (Generated reviews,
        per-object (template, enforcementAction) overrides)."""
        if getattr(self.evaluator, "renders", False):
            # sidecar lane: the sweep RPC already rendered kept violations
            # and covered every constraint (incl. non-lowered kinds)
            for (ckind, cname), (total, kept_list) in swept.items():
                key = (ckind, cname)
                if key not in totals:
                    continue
                totals[key] += total
                con = self.client.get_constraint(ckind, cname)
                for oi, msg, details in kept_list:
                    if con is not None and len(kept[key]) < limit:
                        kept[key].append(
                            self._violation(con, objects[oi], msg, details))
            return
        target = self.client.target
        driver = next(
            (d for d in self.client.drivers if hasattr(d, "query_batch")),
            None,
        )
        review_cache: dict = {}

        def get_review(oi):
            # per-index lazy: a chunk renders only its kept hits, so
            # building every review up front is O(chunk) waste
            r = review_cache.get(oi)
            if r is None:
                r = target.handle_review(AugmentedUnstructured(
                    object=objects[oi], source=source))
                review_cache[oi] = r
            return r

        def get_reviews():
            return [get_review(oi) for oi in range(len(objects))]

        exact = self.config.exact_totals
        cfg = ReviewCfg(enforcement_point=AUDIT_EP)

        def render(con, oi):
            self.perf["n_renders"] = self.perf.get("n_renders", 0) + 1
            t0 = time.perf_counter()
            if hasattr(driver, "render_query"):
                results = driver.render_query(
                    self.client.target.name, con, get_review(oi), cfg
                ).results
            else:
                results = driver._interp.query(
                    self.client.target.name, [con], get_review(oi), cfg
                ).results
            self._attr_render(con, time.perf_counter() - t0)
            return results

        for con, total, kept_list in self.fold_swept(
                swept, len(objects), render, limit, exact,
                budget=lambda con: limit - len(kept[con.key()])):
            key = con.key()
            totals[key] += total
            for oi, msg, details in kept_list:
                if len(kept[key]) < limit:
                    kept[key].append(
                        self._violation(con, objects[oi], msg, details,
                                        override=(overrides[oi]
                                                  if overrides else None)))
        # everything the device sweep did not cover (non-lowered kinds, CEL
        # templates owned by another driver, inventory-inexact referential
        # kinds) goes through its own driver's exact path
        rest = [c for c in constraints if c.kind not in swept]
        if rest:
            self._eval_via_drivers(rest, objects, get_reviews(), kept,
                                   totals, limit, overrides=overrides)

    def _chunk_via_query_batch(self, driver, constraints, objects, reviews,
                               kept, totals, limit, overrides=None):
        responses = driver.query_batch(
            self.client.target.name, constraints, reviews,
            ReviewCfg(enforcement_point=AUDIT_EP),
        )
        for oi, resp in enumerate(responses):
            for r in resp.results:
                ckind = r.constraint.get("kind", "")
                cname = (r.constraint.get("metadata") or {}).get("name", "")
                key = (ckind, cname)
                if key not in totals:
                    continue
                totals[key] += 1
                if len(kept[key]) < limit:
                    con = self.client.get_constraint(ckind, cname)
                    kept[key].append(
                        self._violation(con, objects[oi], r.msg, r.details,
                                        override=(overrides[oi]
                                                  if overrides else None))
                    )

    def _violation(self, con, obj, msg, details,
                   override=None) -> Violation:
        group, version, kind = gvk_of(obj)
        meta = obj.get("metadata") or {}
        actions = con.actions_for(AUDIT_EP)
        action = actions[0] if actions else con.enforcement_action
        if override is not None:
            # expansion generator stage: the [Implied by <template>]
            # message prefix and the template's enforcementAction
            # override (reference: expansion/aggregate.go semantics)
            template_name, override_action = override
            from gatekeeper_tpu.expansion.aggregate import \
                CHILD_MSG_PREFIX

            msg = f"{CHILD_MSG_PREFIX % template_name} {msg}"
            if override_action:
                action = override_action
        return Violation(
            constraint=con,
            message=msg,
            enforcement_action=action,
            group=group,
            version=version,
            kind=kind,
            name=meta.get("name", "") or "",
            namespace=meta.get("namespace", "") or "",
            details=details,
        )

    # --- status writeback (reference: writeAuditResults, manager.go:947) -
    def _write_statuses(self, run: AuditRun, constraints):
        for con in constraints:
            key = con.key()
            status = {
                "auditTimestamp": run.timestamp,
                "totalViolations": run.total_violations.get(key, 0),
                # explicit partial-result marker (chunks were dropped
                # after retries or the lister died): totals undercount.
                # Only written when set so complete runs keep the
                # reference status shape byte-for-byte
                **({"incomplete": True} if run.incomplete else {}),
                "violations": [
                    {
                        "message": v.message,
                        "enforcementAction": v.enforcement_action,
                        "group": v.group,
                        "version": v.version,
                        "kind": v.kind,
                        "name": v.name,
                        "namespace": v.namespace,
                    }
                    for v in run.kept.get(key, [])
                ],
            }
            if self.status_writer is not None:
                self.status_writer(con, status)
            else:
                con.raw.setdefault("status", {}).update(status)

    def _finish(self, run: AuditRun):
        if self.export_system is not None:
            for key, violations in run.kept.items():
                for v in violations:
                    self.export_system.publish_violation(run.timestamp, v)
            self.export_system.publish_audit_ended(run.timestamp)
        if self.log_violations:
            from gatekeeper_tpu.utils.logging import log_audit_violation

            for violations in run.kept.values():
                for v in violations:
                    log_audit_violation(v, run.timestamp)
        if self.event_sink is not None:
            self.event_sink(run)


def _now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
