"""Startup CRD storage-version migration (upgrade manager).

Reference: pkg/upgrade/manager.go:31-60 — on boot, gatekeeper lists its
own CRDs and re-writes ``status.storedVersions`` so that decommissioned
API versions (v1alpha1/v1beta1 cleanup) can be dropped from etcd before a
future release removes them from the CRD spec.

Why this is nearly n/a in this framework's shape: every CRD this
framework synthesizes (constraint kinds from templates, the framework's
own types) is served at a SINGLE version, and all state reconstructs
from the apiserver on boot (SURVEY.md §5.4) — there is no multi-version
stored state to migrate.  The manager below still performs the
reference-equivalent contract so operators upgrading from a cluster
previously managed by the Go reference (whose CRDs may carry legacy
stored versions) converge: any stored version no longer present in a
CRD's ``spec.versions`` is pruned from ``status.storedVersions``,
keeping at most the served versions.

Wired by ``controller.manager`` at startup (one pass; the reference runs
it once per boot too).
"""

from __future__ import annotations

from typing import Optional

from gatekeeper_tpu.utils.logging import log_event

CRD_GVK = ("apiextensions.k8s.io", "v1", "CustomResourceDefinition")

# CRD groups this framework owns (reference: upgrade manager only touches
# gatekeeper CRDs — constraints + its own API groups)
OWNED_GROUP_SUFFIXES = (
    "gatekeeper.sh",
)


def _owned(crd: dict) -> bool:
    group = ((crd.get("spec") or {}).get("group")) or ""
    return any(group == s or group.endswith("." + s)
               for s in OWNED_GROUP_SUFFIXES)


class UpgradeManager:
    """One-shot stored-version migration over an ObjectSource cluster."""

    def __init__(self, cluster):
        self.cluster = cluster

    def upgrade(self) -> int:
        """Prune stale entries from ``status.storedVersions`` of every
        owned CRD; returns the number of CRDs migrated."""
        try:
            crds = self.cluster.list(CRD_GVK)
        except Exception as e:  # discovery may not serve CRDs (tests)
            log_event("info", f"upgrade: CRD list unavailable: {e}",
                      process="upgrade")
            return 0
        migrated = 0
        for crd in crds or []:
            if not _owned(crd):
                continue
            spec_versions = [
                v.get("name") for v in
                ((crd.get("spec") or {}).get("versions") or [])
                if isinstance(v, dict)
            ]
            status = crd.get("status") or {}
            stored = list(status.get("storedVersions") or [])
            kept = [v for v in stored if v in spec_versions]
            if kept == stored:
                continue
            crd = dict(crd)
            crd["status"] = dict(status)
            crd["status"]["storedVersions"] = kept
            try:
                # CRD status is a subresource on a real apiserver: a main
                # PUT silently drops it (found in round-3 review)
                write = getattr(self.cluster, "apply_status",
                                self.cluster.apply)
                write(crd)
                migrated += 1
                log_event(
                    "info",
                    "upgrade: pruned storedVersions of "
                    f"{(crd.get('metadata') or {}).get('name')}: "
                    f"{stored} -> {kept}",
                    process="upgrade",
                )
            except Exception as e:
                log_event(
                    "warning",
                    "upgrade: migrating "
                    f"{(crd.get('metadata') or {}).get('name')} "
                    f"failed: {e}",
                    process="upgrade",
                )
        return migrated


def run_upgrade(cluster) -> Optional[int]:
    """Convenience wrapper used by the controller manager at boot."""
    return UpgradeManager(cluster).upgrade()
