"""Reconciliation manager: cluster state → framework state.

The reference wires 17 controllers over controller-runtime
(pkg/controller/controller.go:178-293); the equivalents here subscribe to the
cluster source and reconcile each resource family into its system:

- ConstraintTemplate → client.add_template (+ dynamic constraint-kind watch,
  mirroring constrainttemplate_controller.go:516) → constraints →
  client.add_constraint
- Config → process excluder + CacheManager.upsert_source (config_controller)
- SyncSet → CacheManager.upsert_source (syncset_controller)
- Assign/AssignMetadata/ModifySet/AssignImage → mutation system
- ExpansionTemplate → expansion system
- Provider → provider cache
- Connection → export system

Operation gating mirrors ``--operation`` pod sharding
(pkg/operations/operations.go): a webhook pod runs no audit, the audit pod
serves no admission — both reconcile the shared state.
"""

from __future__ import annotations

import sys
import threading
from typing import Iterable, Optional

from gatekeeper_tpu.apis.constraints import CONSTRAINTS_GROUP
from gatekeeper_tpu.expansion.system import EXPANSION_GROUP, ExpansionSystem
from gatekeeper_tpu.externaldata.providers import PROVIDER_GROUP, ProviderCache
from gatekeeper_tpu.mutation.mutators import MUTATIONS_GROUP, MUTATOR_KINDS
from gatekeeper_tpu.mutation.system import MutationSystem
from gatekeeper_tpu.readiness.tracker import Tracker
from gatekeeper_tpu.sync.cachemanager import CacheManager
from gatekeeper_tpu.sync.process import ProcessExcluder
from gatekeeper_tpu.sync.source import DELETED, Event, FakeCluster
from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of, name_of

TEMPLATES_GVK = ("templates.gatekeeper.sh", "v1", "ConstraintTemplate")
CONFIG_GVK = ("config.gatekeeper.sh", "v1alpha1", "Config")
SYNCSET_GVK = ("syncset.gatekeeper.sh", "v1alpha1", "SyncSet")
EXPANSION_GVK = (EXPANSION_GROUP, "v1alpha1", "ExpansionTemplate")
PROVIDER_GVK = (PROVIDER_GROUP, "v1beta1", "Provider")
CONNECTION_GVK = ("connection.gatekeeper.sh", "v1alpha1", "Connection")
WEBHOOKCONFIG_GVK = ("admissionregistration.k8s.io", "v1",
                     "ValidatingWebhookConfiguration")

ALL_OPERATIONS = ("audit", "webhook", "mutation-webhook",
                  "mutation-controller", "status", "generate")

# per-pod status substrate (reference: apis/status/v1beta1)
STATUS_GROUP = "status.gatekeeper.sh"
STATUS_VERSION = "v1beta1"
STATUS_KIND_FOR = {
    "ConstraintTemplate": "ConstraintTemplatePodStatus",
    CONSTRAINTS_GROUP: "ConstraintPodStatus",
    "Config": "ConfigPodStatus",
    "ExpansionTemplate": "ExpansionTemplatePodStatus",
    "Assign": "MutatorPodStatus",
    "AssignMetadata": "MutatorPodStatus",
    "ModifySet": "MutatorPodStatus",
    "AssignImage": "MutatorPodStatus",
    "Provider": "ExternalDataProviderPodStatus",
    "Connection": "ConnectionPodStatus",
}


class Manager:
    def __init__(
        self,
        client,
        cluster: FakeCluster,
        operations: Iterable[str] = ALL_OPERATIONS,
        mutation_system: Optional[MutationSystem] = None,
        expansion_system: Optional[ExpansionSystem] = None,
        provider_cache: Optional[ProviderCache] = None,
        extdata_lane=None,  # extdata/lane.ExtDataLane
        export_system=None,
        metrics=None,
        pod_name: Optional[str] = None,
        readiness_retries: int = 0,
    ):
        import os

        self.client = client
        self.cluster = cluster
        self.operations = set(operations)
        self.pod_name = pod_name or os.environ.get(
            "POD_NAME", "gatekeeper-tpu-0")
        self.tracker = Tracker(retries=readiness_retries)
        self.excluder = ProcessExcluder()
        self.webhookconfig_cache = None  # validating webhook match scope
        self.provider_cache = provider_cache or ProviderCache()
        # batched external-data join lane (extdata/lane.py): Provider
        # reconciles invalidate its resident columns so spec changes
        # (URL, CA, timeout) can't serve stale join answers
        self.extdata_lane = extdata_lane
        self.mutation_system = mutation_system or MutationSystem(
            provider_cache=self.provider_cache)
        self.expansion_system = expansion_system or ExpansionSystem(
            mutation_system=self.mutation_system)
        self.export_system = export_system
        self.metrics = metrics
        self.cache_manager = CacheManager(
            client, cluster, excluder=self.excluder,
            readiness_tracker=self.tracker, metrics=metrics,
        )
        self._constraint_watches: dict[str, callable] = {}  # kind -> cancel
        self._lock = threading.RLock()
        self._template_errors: dict[str, str] = {}
        self._requeue_delay: dict[str, float] = {}  # backoff continuity
        # Config spec.validation.traces[] (per-request webhook tracing)
        self.validation_traces: list = []

    def is_assigned(self, op: str) -> bool:
        """Reference: operations.IsAssigned (operations.go:92)."""
        return op in self.operations or "*" in self.operations

    # --- generation swap (drivers/generation.py) ------------------------
    def generation_coordinator(self):
        """The TPU driver's GenerationCoordinator, or None (no TPU
        driver / --generation-swap off)."""
        for d in self.client.drivers:
            gc = getattr(d, "gen_coord", None)
            if gc is not None:
                return gc
        return None

    def begin_background_compile(self) -> bool:
        """Flip template reconciles from inline compile to the
        enqueue-and-swap lane.  Called once boot reconcile has settled
        (manifests loaded, warm pass done): boot stays synchronous —
        readiness and the warm loop see compiled templates — while every
        LATER reconcile only stages + notifies; the background thread
        compiles the next generation and swaps it in off the serving
        path.  Returns True when a coordinator exists and is running."""
        gc = self.generation_coordinator()
        if gc is None:
            return False
        gc.start()
        return True

    # --- boot (reference: readiness tracker seeding, ready_tracker.go:326)
    def start(self) -> "Manager":
        # stored-version migration first (reference: pkg/upgrade runs
        # before controllers, manager.go:31-60) — prunes legacy
        # storedVersions from owned CRDs left by older deployments
        from gatekeeper_tpu.controller.upgrade import run_upgrade

        run_upgrade(self.cluster)

        def boot_list(gvk):
            # a missing CRD / transient apiserver error must not crash
            # boot: the watch plane retries with backoff, readiness just
            # starts with zero expectations for that kind
            try:
                return self.cluster.list(gvk)
            except Exception as e:
                print(f"boot list {gvk}: {e}", file=sys.stderr)  # noqa: T201
                return []

        boot_templates = boot_list(TEMPLATES_GVK)
        for obj in boot_templates:
            self.tracker.expect("templates", name_of(obj))
        self.tracker.populated("templates")
        # per-template constraint listers (reference: SingleRunner listers
        # per template kind, ready_tracker.go:326): each pre-existing
        # template's constraints become expectations, observed as the
        # dynamic watches reconcile them
        for obj in boot_templates:
            ckind = deep_get(obj,
                             ("spec", "crd", "spec", "names", "kind"), "")
            if not ckind:
                continue
            for con in boot_list((CONSTRAINTS_GROUP, "v1beta1", ckind)):
                self.tracker.expect("constraints",
                                    (ckind, name_of(con)))
        for gvk, kind in ((CONFIG_GVK, "config"),
                          (EXPANSION_GVK, "expansions"),
                          (PROVIDER_GVK, "providers")):
            for obj in boot_list(gvk):
                self.tracker.expect(kind, name_of(obj))
            self.tracker.populated(kind)
        for gvk in [TEMPLATES_GVK, CONFIG_GVK, SYNCSET_GVK, EXPANSION_GVK,
                    PROVIDER_GVK, CONNECTION_GVK, WEBHOOKCONFIG_GVK]:
            self.cluster.subscribe(gvk, self._dispatch, replay=True)
        for mkind in MUTATOR_KINDS:
            for version in ("v1", "v1beta1", "v1alpha1"):
                self.cluster.subscribe((MUTATIONS_GROUP, version, mkind),
                                       self._dispatch, replay=True)
        self.tracker.populated("mutators")
        # status controllers: fold per-pod status CRs into parent status
        for status_kind in sorted(set(STATUS_KIND_FOR.values())):
            self.cluster.subscribe(
                (STATUS_GROUP, STATUS_VERSION, status_kind),
                self._dispatch, replay=True)
        # constraints tracked once their kinds exist; mark populated for the
        # boot snapshot (dynamic watches will observe them)
        self.tracker.populated("constraints")
        self.tracker.populated("data")
        return self

    # --- dispatch -------------------------------------------------------
    def _dispatch(self, event: Event) -> None:
        group, _version, kind = gvk_of(event.obj)
        if event.type == DELETED and group != STATUS_GROUP and (
                kind in STATUS_KIND_FOR or group in STATUS_KIND_FOR):
            # every replica removes ITS pod-status with the parent (the
            # reference's status controllers do the same), so recreated
            # parents never fold departed pods' orphans
            self._delete_pod_status(event.obj)
        try:
            if (group, kind) == (TEMPLATES_GVK[0], TEMPLATES_GVK[2]):
                self._reconcile_template(event)
            elif group == CONSTRAINTS_GROUP:
                self._reconcile_constraint(event)
            elif (group, kind) == (CONFIG_GVK[0], CONFIG_GVK[2]):
                self._reconcile_config(event)
            elif (group, kind) == (SYNCSET_GVK[0], SYNCSET_GVK[2]):
                self._reconcile_syncset(event)
            elif group == MUTATIONS_GROUP and kind in MUTATOR_KINDS:
                self._reconcile_mutator(event)
            elif (group, kind) == (EXPANSION_GVK[0], EXPANSION_GVK[2]):
                self._reconcile_expansion(event)
            elif (group, kind) == (PROVIDER_GVK[0], PROVIDER_GVK[2]):
                self._reconcile_provider(event)
            elif (group, kind) == (CONNECTION_GVK[0], CONNECTION_GVK[2]):
                self._reconcile_connection(event)
            elif (group, kind) == (WEBHOOKCONFIG_GVK[0],
                                   WEBHOOKCONFIG_GVK[2]):
                self._reconcile_webhookconfig(event)
            elif group == STATUS_GROUP:
                self._reconcile_podstatus(event)
        except Exception as e:  # reconcile errors surface via status
            self._set_status(event.obj, error=str(e))

    # --- per-family reconcilers ----------------------------------------
    def _reconcile_template(self, event: Event) -> None:
        name = name_of(event.obj)
        if event.type == DELETED:
            kind = deep_get(event.obj,
                            ("spec", "crd", "spec", "names", "kind"), "")
            if kind:
                self.client.remove_template(kind)
                cancel = self._constraint_watches.pop(kind, None)
                if cancel:
                    cancel()
                self._prune_constraints_of(kind)
            # a template deleted before its boot expectation was observed
            # must not wedge /readyz (reference CancelExpect on delete)
            self.tracker.cancel("templates", name)
            return
        try:
            crd = self.client.add_template(event.obj)
        except Exception as e:
            # compile failure: cancel the readiness expectation
            # (constrainttemplate_controller.go:391,484) and prune the
            # kind's constraint expectations (they can never be observed)
            self._prune_constraints_of(deep_get(
                event.obj, ("spec", "crd", "spec", "names", "kind"), ""))
            cancelled = self.tracker.try_cancel("templates", name)
            self._template_errors[name] = str(e)
            self._set_status(event.obj, error=str(e))
            if not cancelled:
                # retry budget remains (--readiness-retries > 0 / -1):
                # requeue with backoff until the budget is spent or the
                # template compiles — without this, nothing re-triggers
                # reconcile and /readyz wedges forever (the reference
                # controller requeues failing reconciles)
                delay = self._requeue_delay.pop(name, 1.0)
                self._requeue_delay[name] = min(delay * 2, 30.0)
                self._requeue_template(name, delay)
            return
        self._template_errors.pop(name, None)
        self.tracker.observe("templates", name)
        if self.metrics is not None:
            self.metrics.set_gauge("constraint_templates",
                                   len(self.client.templates()), {})
        kind = crd["spec"]["names"]["kind"]
        try:
            self._manage_vap(event.obj, kind)
        except Exception as e:
            # VAP generation failure is a status condition, never a reconcile
            # abort (the template stays live and its constraints watched)
            self._set_status(event.obj, error=f"vap generation: {e}")
        with self._lock:
            if kind not in self._constraint_watches:
                # dynamic watch for the constraint kind
                # (constrainttemplate_controller.go:516)
                self._constraint_watches[kind] = self.cluster.subscribe(
                    (CONSTRAINTS_GROUP, "v1beta1", kind), self._dispatch,
                    replay=True,
                )
        self._set_status(event.obj, created=True)

    def _prune_constraints_of(self, kind: str) -> None:
        """The kind's constraint expectations die with its template."""
        if kind:
            self.tracker.prune("constraints", lambda k: k[0] == kind)

    def _requeue_template(self, name: str, delay_s: float = 1.0) -> None:
        """Re-reconcile a failing template after a backoff, reading the
        CURRENT object (a delete or a fixed re-apply in the meantime
        wins).  The retry runs the FULL reconcile — on success the
        constraint-kind watch, VAP management, status and metrics all
        happen exactly as for a watch-event reconcile.  The failure path
        doubles the delay (capped 30s) via _requeue_delay; the chain dies
        when the template compiles, is deleted, or try_cancel spends the
        readiness budget."""
        import threading as _threading

        from gatekeeper_tpu.sync.source import MODIFIED, Event

        def fire():
            cur = self.cluster.get(TEMPLATES_GVK, "", name)
            if cur is None or name not in self._template_errors:
                self._requeue_delay.pop(name, None)
                return  # deleted or fixed meanwhile
            self._reconcile_template(Event(MODIFIED, cur))
            if name not in self._template_errors:
                self._requeue_delay.pop(name, None)

        t = _threading.Timer(delay_s, fire)
        t.daemon = True
        t.start()

    def _reconcile_constraint(self, event: Event) -> None:
        if event.type == DELETED:
            self.client.remove_constraint(event.obj)
            # deleted before observed must not wedge readiness
            self.tracker.cancel(
                "constraints",
                (event.obj.get("kind", ""), name_of(event.obj)))
        else:
            self.client.add_constraint(event.obj)
            self.tracker.observe(
                "constraints",
                (event.obj.get("kind", ""), name_of(event.obj)))
            self._manage_vapb(event.obj)
        if self.metrics is not None:
            self.metrics.set_gauge("constraints",
                                   len(self.client.constraints()), {})

    def _reconcile_config(self, event: Event) -> None:
        name = name_of(event.obj)
        # reference enforces the singleton name "config" (policy.go:489-494)
        if name != "config":
            self._set_status(event.obj, error="config name must be 'config'")
            return
        if event.type == DELETED:
            self.cache_manager.remove_source(("config", name))
            # excluder reset must wipe + replay like any excluder change
            self.cache_manager.replace_excluder(ProcessExcluder())
            self.validation_traces = []
            return
        match_entries = deep_get(event.obj, ("spec", "match"), []) or []
        self.cache_manager.replace_excluder(
            ProcessExcluder.from_config_match(match_entries))
        # per-request decision tracing selectors (config_types.go:42-54),
        # consulted by the webhook via Manager.validation_traces
        self.validation_traces = deep_get(
            event.obj, ("spec", "validation", "traces"), []) or []
        gvks = []
        for e in deep_get(event.obj, ("spec", "sync", "syncOnly"), []) or []:
            gvks.append((e.get("group", ""), e.get("version", ""),
                        e.get("kind", "")))
        self.cache_manager.upsert_source(("config", name), gvks)
        self.tracker.observe("config", name)

    def _reconcile_syncset(self, event: Event) -> None:
        name = name_of(event.obj)
        if event.type == DELETED:
            self.cache_manager.remove_source(("syncset", name))
            return
        gvks = []
        for e in deep_get(event.obj, ("spec", "gvks"), []) or []:
            gvks.append((e.get("group", ""), e.get("version", ""),
                        e.get("kind", "")))
        self.cache_manager.upsert_source(("syncset", name), gvks)

    def _reconcile_mutator(self, event: Event) -> None:
        from gatekeeper_tpu.mutation.mutators import MutatorID

        _g, _v, kind = gvk_of(event.obj)
        if event.type == DELETED:
            self.mutation_system.remove(
                MutatorID(kind=kind, name=name_of(event.obj)))
        else:
            self.mutation_system.upsert_unstructured(event.obj)
            if self.metrics is not None:
                self.metrics.inc_counter(
                    "mutator_ingestion_count", {"status": "active"})
                self.metrics.set_gauge(
                    "mutator_conflicting_count",
                    len(self.mutation_system.conflicts()), {})

    def _reconcile_expansion(self, event: Event) -> None:
        if event.type == DELETED:
            self.expansion_system.remove_template(name_of(event.obj))
        else:
            self.expansion_system.upsert_template(event.obj)
            self.tracker.observe("expansions", name_of(event.obj))

    def _reconcile_provider(self, event: Event) -> None:
        name = name_of(event.obj)
        if event.type == DELETED:
            self.provider_cache.remove(name)
        else:
            self.provider_cache.upsert(event.obj)
            self.tracker.observe("providers", name)
        if self.extdata_lane is not None:
            # belt-and-braces with the ProviderCache listener: a lane
            # wired to a DIFFERENT cache still invalidates on reconcile
            self.extdata_lane.invalidate(name)

    def _reconcile_connection(self, event: Event) -> None:
        if self.export_system is None:
            return
        if event.type == DELETED:
            self.export_system.remove_connection(name_of(event.obj))
        else:
            self.export_system.upsert_connection_cr(event.obj)

    # --- status (reference: per-pod *PodStatus CRs folded by status
    # controllers; single-process equivalent writes .status directly) ----
    # --- per-pod status CRs (reference: apis/status/v1beta1 + the 7
    # status controllers, e.g. constraintstatus_controller.go:251) -------
    def _set_status(self, obj: dict, error: Optional[str] = None,
                    created: bool = False) -> None:
        """Write THIS pod's status as a namespaced *PodStatus object; the
        status fold (_reconcile_podstatus, running in every replica)
        aggregates all pods' entries into the parent's .status.byPod —
        the reference's multi-replica coordination substrate (no leader
        election; per-pod CRs avoid write contention)."""
        group, version, kind = gvk_of(obj)
        status_kind = STATUS_KIND_FOR.get(
            kind if kind in STATUS_KIND_FOR else group)
        name = name_of(obj)
        namespace = deep_get(obj, ("metadata", "namespace"), "") or ""
        if status_kind is None or not name:
            return
        entry = {
            "id": self.pod_name,
            "observedGeneration": deep_get(
                obj, ("metadata", "generation"), 1),
            "operations": sorted(self.operations),
        }
        if error is not None:
            entry["errors"] = [{"message": error}]
        pod_status = {
            "apiVersion": f"{STATUS_GROUP}/{STATUS_VERSION}",
            "kind": status_kind,
            "metadata": {
                "name": f"{self.pod_name}-{kind}-{name}".lower(),
                "namespace": "gatekeeper-system",
                "labels": {
                    "internal.gatekeeper.sh/pod": self.pod_name,
                    "internal.gatekeeper.sh/parent-kind": kind,
                    "internal.gatekeeper.sh/parent-name": name,
                    "internal.gatekeeper.sh/parent-group": group,
                    "internal.gatekeeper.sh/parent-version": version,
                    "internal.gatekeeper.sh/parent-namespace": namespace,
                },
            },
            "status": {**entry, "created": created},
        }
        existing = self.cluster.get(
            (STATUS_GROUP, STATUS_VERSION, status_kind),
            "gatekeeper-system", pod_status["metadata"]["name"])
        if existing is not None and \
                existing.get("status") == pod_status["status"]:
            # unchanged PodStatus won't fire the watch, but the PARENT may
            # have been rewritten without status (spec update): refold
            self._fold_parent(status_kind, kind, name, group, version,
                              namespace)
            return
        self.cluster.apply(pod_status)

    def _delete_pod_status(self, obj: dict) -> None:
        group, version, kind = gvk_of(obj)
        status_kind = STATUS_KIND_FOR.get(
            kind if kind in STATUS_KIND_FOR else group)
        name = name_of(obj)
        if status_kind is None or not name:
            return
        self.cluster.delete({
            "apiVersion": f"{STATUS_GROUP}/{STATUS_VERSION}",
            "kind": status_kind,
            "metadata": {
                "name": f"{self.pod_name}-{kind}-{name}".lower(),
                "namespace": "gatekeeper-system",
            },
        })

    def _reconcile_podstatus(self, event: Event) -> None:
        """Fold every pod's *PodStatus for one parent into the parent's
        .status.byPod (the reference's status controllers)."""
        labels = deep_get(event.obj, ("metadata", "labels"), {}) or {}
        p_kind = labels.get("internal.gatekeeper.sh/parent-kind", "")
        p_name = labels.get("internal.gatekeeper.sh/parent-name", "")
        p_group = labels.get("internal.gatekeeper.sh/parent-group", "")
        p_version = labels.get("internal.gatekeeper.sh/parent-version", "")
        p_ns = labels.get("internal.gatekeeper.sh/parent-namespace", "")
        if not p_kind or not p_name:
            return
        _g, _v, status_kind = gvk_of(event.obj)
        self._fold_parent(status_kind, p_kind, p_name, p_group, p_version,
                          p_ns)

    def _fold_parent(self, status_kind, p_kind, p_name, p_group,
                     p_version, p_namespace: str = "") -> None:
        entries = []
        created = False
        for ps in self.cluster.list(
                (STATUS_GROUP, STATUS_VERSION, status_kind)):
            pl = deep_get(ps, ("metadata", "labels"), {}) or {}
            if pl.get("internal.gatekeeper.sh/parent-kind") != p_kind or \
                    pl.get("internal.gatekeeper.sh/parent-name") != p_name:
                continue
            st = dict(ps.get("status") or {})
            created = created or bool(st.pop("created", False))
            entries.append(st)
        entries.sort(key=lambda e: e.get("id", ""))
        parent = self.cluster.get((p_group, p_version, p_kind),
                                  p_namespace, p_name)
        if parent is None:
            return
        status = dict(parent.get("status") or {})
        if status.get("byPod") == entries and \
                status.get("created", False) == created:
            return  # converged: break the reconcile echo
        status["byPod"] = entries
        status["created"] = created
        updated = dict(parent)
        updated["status"] = status
        self.cluster.apply(updated)

    def template_error(self, name: str) -> Optional[str]:
        return self._template_errors.get(name)

    # --- VAP generation (reference: manageVAP at constrainttemplate_
    # controller.go:503-524 + manageVAPB at constraint_controller.go:375;
    # gated by generateVAP in the CEL source) ---------------------------
    def _cel_driver(self):
        for d in self.client.drivers:
            if hasattr(d, "template_to_vap"):
                return d
        return None

    def _reconcile_webhookconfig(self, event: Event) -> None:
        """webhookconfig cache (reference: webhookconfig_controller.go:293
        + webhookconfigcache/): cache the validating webhook's match scope
        so generated VAPs mirror it, then refresh every generated VAP."""
        if event.type == "DELETED":
            self.webhookconfig_cache = None
        else:
            hooks = event.obj.get("webhooks") or []
            scope = {}
            for h in hooks:
                if "validation" not in h.get("name", ""):
                    continue
                scope = {
                    "namespaceSelector": h.get("namespaceSelector"),
                    "objectSelector": h.get("objectSelector"),
                    "rules": h.get("rules"),
                }
                break
            self.webhookconfig_cache = scope or None
        # re-emit VAPs for every CEL template under the new scope
        for tobj in self.cluster.list(TEMPLATES_GVK):
            kind = (((tobj.get("spec") or {}).get("crd") or {})
                    .get("spec") or {}).get("names", {}).get("kind")
            if kind:
                self._manage_vap(tobj, kind)

    def _manage_vap(self, template_obj: dict, kind: str) -> None:
        driver = self._cel_driver()
        if driver is None:
            return
        compiled = getattr(driver, "_templates", {}).get(kind)
        if compiled is None or not getattr(compiled, "generate_vap", False):
            return
        from gatekeeper_tpu.apis.templates import ConstraintTemplate

        t = ConstraintTemplate.from_unstructured(template_obj)
        self.cluster.apply(driver.template_to_vap(
            t, webhook_scope=self.webhookconfig_cache))

    def _manage_vapb(self, constraint_obj: dict) -> None:
        driver = self._cel_driver()
        if driver is None:
            return
        kind = constraint_obj.get("kind", "")
        compiled = getattr(driver, "_templates", {}).get(kind)
        if compiled is None or not getattr(compiled, "generate_vap", False):
            return
        from gatekeeper_tpu.apis.constraints import Constraint

        template = self.client.get_template(kind)
        if template is None:
            return
        con = Constraint.from_unstructured(constraint_obj)
        self.cluster.apply(driver.constraint_to_vap_binding(con, template))
