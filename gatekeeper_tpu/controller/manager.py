"""Reconciliation manager: cluster state → framework state.

The reference wires 17 controllers over controller-runtime
(pkg/controller/controller.go:178-293); the equivalents here subscribe to the
cluster source and reconcile each resource family into its system:

- ConstraintTemplate → client.add_template (+ dynamic constraint-kind watch,
  mirroring constrainttemplate_controller.go:516) → constraints →
  client.add_constraint
- Config → process excluder + CacheManager.upsert_source (config_controller)
- SyncSet → CacheManager.upsert_source (syncset_controller)
- Assign/AssignMetadata/ModifySet/AssignImage → mutation system
- ExpansionTemplate → expansion system
- Provider → provider cache
- Connection → export system

Operation gating mirrors ``--operation`` pod sharding
(pkg/operations/operations.go): a webhook pod runs no audit, the audit pod
serves no admission — both reconcile the shared state.
"""

from __future__ import annotations

import sys
import threading
from typing import Iterable, Optional

from gatekeeper_tpu.apis.constraints import CONSTRAINTS_GROUP
from gatekeeper_tpu.expansion.system import EXPANSION_GROUP, ExpansionSystem
from gatekeeper_tpu.externaldata.providers import PROVIDER_GROUP, ProviderCache
from gatekeeper_tpu.mutation.mutators import MUTATIONS_GROUP, MUTATOR_KINDS
from gatekeeper_tpu.mutation.system import MutationSystem
from gatekeeper_tpu.readiness.tracker import Tracker
from gatekeeper_tpu.sync.cachemanager import CacheManager
from gatekeeper_tpu.sync.process import ProcessExcluder
from gatekeeper_tpu.sync.source import DELETED, Event, FakeCluster
from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of, name_of

TEMPLATES_GVK = ("templates.gatekeeper.sh", "v1", "ConstraintTemplate")
CONFIG_GVK = ("config.gatekeeper.sh", "v1alpha1", "Config")
SYNCSET_GVK = ("syncset.gatekeeper.sh", "v1alpha1", "SyncSet")
EXPANSION_GVK = (EXPANSION_GROUP, "v1alpha1", "ExpansionTemplate")
PROVIDER_GVK = (PROVIDER_GROUP, "v1beta1", "Provider")
CONNECTION_GVK = ("connection.gatekeeper.sh", "v1alpha1", "Connection")
WEBHOOKCONFIG_GVK = ("admissionregistration.k8s.io", "v1",
                     "ValidatingWebhookConfiguration")

ALL_OPERATIONS = ("audit", "webhook", "mutation-webhook",
                  "mutation-controller", "status", "generate")


class Manager:
    def __init__(
        self,
        client,
        cluster: FakeCluster,
        operations: Iterable[str] = ALL_OPERATIONS,
        mutation_system: Optional[MutationSystem] = None,
        expansion_system: Optional[ExpansionSystem] = None,
        provider_cache: Optional[ProviderCache] = None,
        export_system=None,
        metrics=None,
    ):
        self.client = client
        self.cluster = cluster
        self.operations = set(operations)
        self.tracker = Tracker()
        self.excluder = ProcessExcluder()
        self.webhookconfig_cache = None  # validating webhook match scope
        self.provider_cache = provider_cache or ProviderCache()
        self.mutation_system = mutation_system or MutationSystem(
            provider_cache=self.provider_cache)
        self.expansion_system = expansion_system or ExpansionSystem(
            mutation_system=self.mutation_system)
        self.export_system = export_system
        self.metrics = metrics
        self.cache_manager = CacheManager(
            client, cluster, excluder=self.excluder,
            readiness_tracker=self.tracker, metrics=metrics,
        )
        self._constraint_watches: dict[str, callable] = {}  # kind -> cancel
        self._lock = threading.RLock()
        self._template_errors: dict[str, str] = {}

    def is_assigned(self, op: str) -> bool:
        """Reference: operations.IsAssigned (operations.go:92)."""
        return op in self.operations or "*" in self.operations

    # --- boot (reference: readiness tracker seeding, ready_tracker.go:326)
    def start(self) -> "Manager":
        def boot_list(gvk):
            # a missing CRD / transient apiserver error must not crash
            # boot: the watch plane retries with backoff, readiness just
            # starts with zero expectations for that kind
            try:
                return self.cluster.list(gvk)
            except Exception as e:
                print(f"boot list {gvk}: {e}", file=sys.stderr)  # noqa: T201
                return []

        for obj in boot_list(TEMPLATES_GVK):
            self.tracker.expect("templates", name_of(obj))
        self.tracker.populated("templates")
        for gvk, kind in ((CONFIG_GVK, "config"),
                          (EXPANSION_GVK, "expansions"),
                          (PROVIDER_GVK, "providers")):
            for obj in boot_list(gvk):
                self.tracker.expect(kind, name_of(obj))
            self.tracker.populated(kind)
        for gvk in [TEMPLATES_GVK, CONFIG_GVK, SYNCSET_GVK, EXPANSION_GVK,
                    PROVIDER_GVK, CONNECTION_GVK, WEBHOOKCONFIG_GVK]:
            self.cluster.subscribe(gvk, self._dispatch, replay=True)
        for mkind in MUTATOR_KINDS:
            for version in ("v1", "v1beta1", "v1alpha1"):
                self.cluster.subscribe((MUTATIONS_GROUP, version, mkind),
                                       self._dispatch, replay=True)
        self.tracker.populated("mutators")
        # constraints tracked once their kinds exist; mark populated for the
        # boot snapshot (dynamic watches will observe them)
        self.tracker.populated("constraints")
        self.tracker.populated("data")
        return self

    # --- dispatch -------------------------------------------------------
    def _dispatch(self, event: Event) -> None:
        group, _version, kind = gvk_of(event.obj)
        try:
            if (group, kind) == (TEMPLATES_GVK[0], TEMPLATES_GVK[2]):
                self._reconcile_template(event)
            elif group == CONSTRAINTS_GROUP:
                self._reconcile_constraint(event)
            elif (group, kind) == (CONFIG_GVK[0], CONFIG_GVK[2]):
                self._reconcile_config(event)
            elif (group, kind) == (SYNCSET_GVK[0], SYNCSET_GVK[2]):
                self._reconcile_syncset(event)
            elif group == MUTATIONS_GROUP and kind in MUTATOR_KINDS:
                self._reconcile_mutator(event)
            elif (group, kind) == (EXPANSION_GVK[0], EXPANSION_GVK[2]):
                self._reconcile_expansion(event)
            elif (group, kind) == (PROVIDER_GVK[0], PROVIDER_GVK[2]):
                self._reconcile_provider(event)
            elif (group, kind) == (CONNECTION_GVK[0], CONNECTION_GVK[2]):
                self._reconcile_connection(event)
            elif (group, kind) == (WEBHOOKCONFIG_GVK[0],
                                   WEBHOOKCONFIG_GVK[2]):
                self._reconcile_webhookconfig(event)
        except Exception as e:  # reconcile errors surface via status
            self._set_status(event.obj, error=str(e))

    # --- per-family reconcilers ----------------------------------------
    def _reconcile_template(self, event: Event) -> None:
        name = name_of(event.obj)
        if event.type == DELETED:
            kind = deep_get(event.obj,
                            ("spec", "crd", "spec", "names", "kind"), "")
            if kind:
                self.client.remove_template(kind)
                cancel = self._constraint_watches.pop(kind, None)
                if cancel:
                    cancel()
            return
        try:
            crd = self.client.add_template(event.obj)
        except Exception as e:
            # compile failure: cancel the readiness expectation
            # (constrainttemplate_controller.go:391,484)
            self.tracker.try_cancel("templates", name)
            self._template_errors[name] = str(e)
            self._set_status(event.obj, error=str(e))
            return
        self._template_errors.pop(name, None)
        self.tracker.observe("templates", name)
        if self.metrics is not None:
            self.metrics.set_gauge("constraint_templates",
                                   len(self.client.templates()), {})
        kind = crd["spec"]["names"]["kind"]
        try:
            self._manage_vap(event.obj, kind)
        except Exception as e:
            # VAP generation failure is a status condition, never a reconcile
            # abort (the template stays live and its constraints watched)
            self._set_status(event.obj, error=f"vap generation: {e}")
        with self._lock:
            if kind not in self._constraint_watches:
                # dynamic watch for the constraint kind
                # (constrainttemplate_controller.go:516)
                self._constraint_watches[kind] = self.cluster.subscribe(
                    (CONSTRAINTS_GROUP, "v1beta1", kind), self._dispatch,
                    replay=True,
                )
        self._set_status(event.obj, created=True)

    def _reconcile_constraint(self, event: Event) -> None:
        if event.type == DELETED:
            self.client.remove_constraint(event.obj)
        else:
            self.client.add_constraint(event.obj)
            self.tracker.observe(
                "constraints",
                (event.obj.get("kind", ""), name_of(event.obj)))
            self._manage_vapb(event.obj)
        if self.metrics is not None:
            self.metrics.set_gauge("constraints",
                                   len(self.client.constraints()), {})

    def _reconcile_config(self, event: Event) -> None:
        name = name_of(event.obj)
        # reference enforces the singleton name "config" (policy.go:489-494)
        if name != "config":
            self._set_status(event.obj, error="config name must be 'config'")
            return
        if event.type == DELETED:
            self.cache_manager.remove_source(("config", name))
            # excluder reset must wipe + replay like any excluder change
            self.cache_manager.replace_excluder(ProcessExcluder())
            return
        match_entries = deep_get(event.obj, ("spec", "match"), []) or []
        self.cache_manager.replace_excluder(
            ProcessExcluder.from_config_match(match_entries))
        gvks = []
        for e in deep_get(event.obj, ("spec", "sync", "syncOnly"), []) or []:
            gvks.append((e.get("group", ""), e.get("version", ""),
                        e.get("kind", "")))
        self.cache_manager.upsert_source(("config", name), gvks)
        self.tracker.observe("config", name)

    def _reconcile_syncset(self, event: Event) -> None:
        name = name_of(event.obj)
        if event.type == DELETED:
            self.cache_manager.remove_source(("syncset", name))
            return
        gvks = []
        for e in deep_get(event.obj, ("spec", "gvks"), []) or []:
            gvks.append((e.get("group", ""), e.get("version", ""),
                        e.get("kind", "")))
        self.cache_manager.upsert_source(("syncset", name), gvks)

    def _reconcile_mutator(self, event: Event) -> None:
        from gatekeeper_tpu.mutation.mutators import MutatorID

        _g, _v, kind = gvk_of(event.obj)
        if event.type == DELETED:
            self.mutation_system.remove(
                MutatorID(kind=kind, name=name_of(event.obj)))
        else:
            self.mutation_system.upsert_unstructured(event.obj)
            if self.metrics is not None:
                self.metrics.inc_counter(
                    "mutator_ingestion_count", {"status": "active"})
                self.metrics.set_gauge(
                    "mutator_conflicting_count",
                    len(self.mutation_system.conflicts()), {})

    def _reconcile_expansion(self, event: Event) -> None:
        if event.type == DELETED:
            self.expansion_system.remove_template(name_of(event.obj))
        else:
            self.expansion_system.upsert_template(event.obj)
            self.tracker.observe("expansions", name_of(event.obj))

    def _reconcile_provider(self, event: Event) -> None:
        if event.type == DELETED:
            self.provider_cache.remove(name_of(event.obj))
        else:
            self.provider_cache.upsert(event.obj)
            self.tracker.observe("providers", name_of(event.obj))

    def _reconcile_connection(self, event: Event) -> None:
        if self.export_system is None:
            return
        if event.type == DELETED:
            self.export_system.remove_connection(name_of(event.obj))
        else:
            self.export_system.upsert_connection_cr(event.obj)

    # --- status (reference: per-pod *PodStatus CRs folded by status
    # controllers; single-process equivalent writes .status directly) ----
    def _set_status(self, obj: dict, error: Optional[str] = None,
                    created: bool = False) -> None:
        status = obj.setdefault("status", {})
        by_pod = status.setdefault("byPod", [{}])
        entry = by_pod[0]
        entry["id"] = "gatekeeper-tpu-0"
        entry["observedGeneration"] = deep_get(
            obj, ("metadata", "generation"), 1)
        if error is not None:
            entry["errors"] = [{"message": error}]
        else:
            entry.pop("errors", None)
        if created:
            status["created"] = True

    def template_error(self, name: str) -> Optional[str]:
        return self._template_errors.get(name)

    # --- VAP generation (reference: manageVAP at constrainttemplate_
    # controller.go:503-524 + manageVAPB at constraint_controller.go:375;
    # gated by generateVAP in the CEL source) ---------------------------
    def _cel_driver(self):
        for d in self.client.drivers:
            if hasattr(d, "template_to_vap"):
                return d
        return None

    def _reconcile_webhookconfig(self, event: Event) -> None:
        """webhookconfig cache (reference: webhookconfig_controller.go:293
        + webhookconfigcache/): cache the validating webhook's match scope
        so generated VAPs mirror it, then refresh every generated VAP."""
        if event.type == "DELETED":
            self.webhookconfig_cache = None
        else:
            hooks = event.obj.get("webhooks") or []
            scope = {}
            for h in hooks:
                if "validation" not in h.get("name", ""):
                    continue
                scope = {
                    "namespaceSelector": h.get("namespaceSelector"),
                    "objectSelector": h.get("objectSelector"),
                    "rules": h.get("rules"),
                }
                break
            self.webhookconfig_cache = scope or None
        # re-emit VAPs for every CEL template under the new scope
        for tobj in self.cluster.list(TEMPLATES_GVK):
            kind = (((tobj.get("spec") or {}).get("crd") or {})
                    .get("spec") or {}).get("names", {}).get("kind")
            if kind:
                self._manage_vap(tobj, kind)

    def _manage_vap(self, template_obj: dict, kind: str) -> None:
        driver = self._cel_driver()
        if driver is None:
            return
        compiled = getattr(driver, "_templates", {}).get(kind)
        if compiled is None or not getattr(compiled, "generate_vap", False):
            return
        from gatekeeper_tpu.apis.templates import ConstraintTemplate

        t = ConstraintTemplate.from_unstructured(template_obj)
        self.cluster.apply(driver.template_to_vap(
            t, webhook_scope=self.webhookconfig_cache))

    def _manage_vapb(self, constraint_obj: dict) -> None:
        driver = self._cel_driver()
        if driver is None:
            return
        kind = constraint_obj.get("kind", "")
        compiled = getattr(driver, "_templates", {}).get(kind)
        if compiled is None or not getattr(compiled, "generate_vap", False):
            return
        from gatekeeper_tpu.apis.constraints import Constraint

        template = self.client.get_template(kind)
        if template is None:
            return
        con = Constraint.from_unstructured(constraint_obj)
        self.cluster.apply(driver.constraint_to_vap_binding(con, template))
