"""ProviderColumn: the resident keyed store of one provider's responses.

The snapshot-store idea (PR 6) applied to external data: responses stay
RESIDENT between bursts/chunks keyed by the raw key string, so a
steady-state burst whose keys are already landed makes zero transport
calls.  Entries expire by TTL (the refresh re-lands them through the
bulk path) and the whole column invalidates when its Provider object is
reconciled (spec change = the cached answers may no longer hold).

A monotone ``version`` bumps on every landing / invalidation; the lane's
vocab-padded device tables key their caches on it, so a warm column
serves the SAME numpy arrays chunk over chunk (the driver's device LRU
then skips the host->device upload too).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class ProviderColumn:
    def __init__(self, provider: str, ttl_s: float = 180.0,
                 clock: Callable[[], float] = time.monotonic):
        self.provider = provider
        self.ttl_s = ttl_s
        self._clock = clock
        # key -> (landed_at, value, error-or-None).  A stale-served
        # refresh re-lands with a fresh stamp: the column's staleness
        # window stacks on the transport cache's own TTL model (bounded,
        # and the breaker paces the retries underneath).
        self._entries: dict = {}
        self._version = 0
        self._lock = threading.Lock()

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def missing(self, keys) -> list:
        """Keys not resident (or past TTL), first-occurrence order,
        deduped — the bulk fetch list."""
        now = self._clock()
        out: list = []
        seen: set = set()
        with self._lock:
            for k in keys:
                if k in seen:
                    continue
                seen.add(k)
                hit = self._entries.get(k)
                if hit is None or now - hit[0] >= self.ttl_s:
                    out.append(k)
        return out

    def land(self, results: dict) -> None:
        """Store ``key -> (value, error-or-None)`` pairs; bumps the
        version (device tables rebuild lazily)."""
        if not results:
            return
        now = self._clock()
        with self._lock:
            for k, (v, e) in results.items():
                self._entries[k] = (now, v, e)
            self._version += 1

    def get(self, key) -> Optional[tuple]:
        """(value, error-or-None) for a resident key, None if never
        landed.  Freshness is ensure()'s job — a key that survived a
        failed refresh reads its last landed value (the stale-serve
        semantics of the transport cache, kept resident)."""
        with self._lock:
            hit = self._entries.get(key)
            return None if hit is None else (hit[1], hit[2])

    def snapshot(self) -> dict:
        """key -> (value, error-or-None) — the table-build read."""
        with self._lock:
            return {k: (v, e) for k, (_t, v, e) in self._entries.items()}

    def invalidate(self) -> None:
        """Provider reconcile: drop everything (the next batch refetches
        through the bulk path)."""
        with self._lock:
            self._entries.clear()
            self._version += 1

    # --- spill persistence (snapshot/persist.py envelope) --------------
    def export_entries(self) -> dict:
        """``key -> (remaining_ttl_s, value, error)`` — absolute clock
        stamps do not survive a restart (the default clock is
        monotonic), so the spill records each key's REMAINING ttl and
        the import re-stamps against the new process's clock."""
        now = self._clock()
        with self._lock:
            return {k: (self.ttl_s - (now - t), v, e)
                    for k, (t, v, e) in self._entries.items()}

    def import_entries(self, entries: dict, elapsed_s: float = 0.0
                       ) -> int:
        """Re-land spilled entries; ``elapsed_s`` is the wall time the
        process spent down (spill ``saved_at`` to load) — keys whose
        remaining TTL it consumed are DROPPED, so a warm restart
        re-fetches only what actually expired.  Returns keys landed."""
        now = self._clock()
        landed = 0
        with self._lock:
            for k, (remaining, v, e) in entries.items():
                remaining -= max(0.0, elapsed_s)
                if remaining <= 0:
                    continue
                self._entries[k] = (now - (self.ttl_s - remaining), v, e)
                landed += 1
            if landed:
                self._version += 1
        return landed
