"""Batched external-data join lane (PAPER.md L5, validation + mutation).

- :mod:`gatekeeper_tpu.extdata.column` — ProviderColumn, the resident
  keyed store (TTL expiry, invalidation on Provider reconcile).
- :mod:`gatekeeper_tpu.extdata.lane` — ExtDataLane: per-batch key
  dedupe, one bulk transport call per (provider, batch) through the
  existing ProviderCache semantics, vocab-padded device join tables,
  batched mutation-placeholder resolution, and the
  batched | perkey | differential lane switch.
"""

from gatekeeper_tpu.extdata.column import ProviderColumn  # noqa: F401
from gatekeeper_tpu.extdata.lane import (  # noqa: F401
    ExtDataDivergence,
    ExtDataLane,
    activate,
    active,
    install,
    uninstall,
)
