"""ExtDataLane: dedupe once per batch, call once per provider, join on
device.

PAPER.md L5 makes external data a first-class input to BOTH validation
and mutation, but the per-key path (``ProviderCache.fetch`` with one key
per call) is a per-object interpreter loop in disguise: at burst scale
the provider round-trips dominate the verdict math.  This lane gives the
external-data join the treatment mutation got in PR 7:

- **key extraction + dedupe** — provider keys referenced by lowered
  templates are pulled from the already-interned vocab sids of the
  flattened batch (drivers/tpu_driver.extdata_cols) and deduped across
  the whole admission burst / audit chunk; mutation placeholders dedupe
  across a convergence pass the same way.
- **one bulk call per (provider, batch)** — ``ensure`` funnels the
  deduped miss list through ``ProviderCache.fetch`` in
  ``max_keys_per_call`` chunks: ONE transport send per chunk, riding the
  existing ``externaldata.send`` span/fault site with the retry /
  breaker / stale-fallback / brownout semantics preserved PER KEY
  (transport failure = per-key stale or error entries, exactly what the
  per-key path would have produced).
- **resident columns** — responses land in :class:`ProviderColumn`
  (TTL + invalidation on Provider reconcile), so steady-state bursts
  hit warm columns with zero transport calls.
- **device join** — ``tables_for`` turns a column into vocab-padded
  ``ext:<provider>:{ok,val}`` arrays the constraint grid reads through
  ir/nodes.ExtDataOk / ExtDataValueSid.

Lane modes (``--extdata-lane``):

- ``batched``: all of the above (the default).
- ``perkey``: the authoritative reference — every resolution is a
  single-key ``ProviderCache.fetch`` and external-data templates stay on
  the exact interpreter (no device tables).
- ``differential``: batched AND per-key per resolution, resolved values
  asserted identical (:class:`ExtDataDivergence` on mismatch); the TPU
  driver additionally asserts device verdicts == interpreter verdicts
  for external-data templates.

Activation mirrors resilience/faults.py: :func:`install` for the
process (``--extdata-lane`` CLI), :func:`activate` for scoped tests; the
Rego ``external_data`` builtin and the mutation system read
:func:`active`.
"""

from __future__ import annotations

import contextvars
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

import numpy as np

from gatekeeper_tpu.extdata.column import ProviderColumn

MODES = ("batched", "perkey", "differential")

# per-key error for keys that were requested but never landed (should
# not happen: ProviderCache.fetch answers every key, value or error)
_NOT_LANDED = "external data: key not resolved"

# the declared provider-response entry schema at the ProviderColumns
# ingest boundary: key -> (json-typed value, error-string-or-None)
_JSON_TYPES = (type(None), bool, int, float, str, list, dict)
_MALFORMED = "malformed provider response"


def validate_landed(landed: dict) -> tuple:
    """Response-schema gate at the ProviderColumns ingest boundary.

    Whatever the transport/cache layer handed back, only well-formed
    ``key -> (json-value, error-or-None)`` entries may land in a
    resident column.  A malformed entry becomes the already-pinned
    per-key failure semantics — an error entry the placeholder failure
    policy handles — never a crash, never a poisoned column; a non-str
    key (nothing requested it, nothing could read it) drops.  Returns
    ``(clean_entries, n_malformed)``."""
    out: dict = {}
    bad = 0
    for key, entry in landed.items():
        if not isinstance(key, str):
            bad += 1
            continue
        if isinstance(entry, (tuple, list)) and len(entry) == 2 \
                and isinstance(entry[0], _JSON_TYPES) \
                and (entry[1] is None or isinstance(entry[1], str)):
            out[key] = (entry[0], entry[1])
        else:
            bad += 1
            out[key] = (None, _MALFORMED)
    return out, bad


class ExtDataDivergence(AssertionError):
    """The batched join disagreed with the per-key reference."""


class ExtDataLane:
    def __init__(self, cache, mode: str = "batched",
                 column_ttl_s: Optional[float] = None,
                 max_keys_per_call: int = 256,
                 metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 fanout: int = 4):
        if mode not in MODES:
            raise ValueError(f"extdata lane mode must be one of {MODES}, "
                             f"got {mode!r}")
        self.cache = cache  # externaldata.providers.ProviderCache
        self.mode = mode
        self.column_ttl_s = (cache.response_ttl_s if column_ttl_s is None
                             else column_ttl_s)
        self.max_keys_per_call = max(1, int(max_keys_per_call))
        self.metrics = metrics
        self._clock = clock
        # per-provider bulk-fetch concurrency (ensure_many): a chunk
        # referencing N providers lands their miss lists across a small
        # thread pool instead of serially; 1 = serial (bit-identical)
        self.fanout = max(1, int(fanout))
        self._pool = None  # lazy ThreadPoolExecutor, daemon threads
        self._columns: dict[str, ProviderColumn] = {}
        # provider -> (column version, covered vocab len, tables dict):
        # reusable while the column is unchanged and every requested key
        # sid is under the covered length (sids interned after the build
        # would clip out of range = a silent miss)
        self._table_cache: dict[str, tuple] = {}
        self._lock = threading.Lock()
        # provider reconcile -> column invalidation (controller/manager
        # reconciles through ProviderCache.upsert/remove)
        add = getattr(cache, "add_listener", None)
        if add is not None:
            add(self._on_provider_change)

    # --- residency -------------------------------------------------------
    def device_join(self) -> bool:
        """True when external-data templates may ride the device grid
        (batched/differential); perkey keeps them on the interpreter."""
        return self.mode != "perkey"

    def column(self, provider: str) -> ProviderColumn:
        with self._lock:
            col = self._columns.get(provider)
            if col is None:
                col = ProviderColumn(provider, ttl_s=self.column_ttl_s,
                                     clock=self._clock)
                self._columns[provider] = col
            return col

    def export_columns(self) -> dict:
        """Spill payload: every resident ProviderColumn's entries with
        per-key remaining TTL (the snapshot spill's extdata section)."""
        with self._lock:
            cols = dict(self._columns)
        return {p: {"ttl_s": col.ttl_s,
                    "entries": col.export_entries()}
                for p, col in cols.items()}

    def import_columns(self, payload: dict, elapsed_s: float = 0.0
                       ) -> int:
        """Re-land spilled columns; ``elapsed_s`` (the wall time since
        the spill was written) consumes each key's remaining TTL, and
        expired keys drop on load — a warm restart re-fetches only what
        actually expired.  Returns total keys landed."""
        landed = 0
        for provider, rec in (payload or {}).items():
            col = self.column(provider)
            landed += col.import_entries(rec.get("entries") or {},
                                         elapsed_s=elapsed_s)
        return landed

    def invalidate(self, provider: Optional[str] = None) -> None:
        with self._lock:
            cols = ([self._columns[provider]]
                    if provider in self._columns else
                    list(self._columns.values()) if provider is None else [])
            if provider is None:
                self._table_cache.clear()
            else:
                self._table_cache.pop(provider, None)
        for col in cols:
            col.invalidate()

    def _on_provider_change(self, name: str) -> None:
        self.invalidate(name)

    def _count_keys(self, provider: str, outcome: str, n: int) -> None:
        if n and self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.EXTDATA_KEYS, {"provider": provider, "outcome": outcome},
                value=float(n))

    def ensure(self, provider: str, keys) -> int:
        """Land every requested key into the provider's column (bulk
        fetch of the deduped miss list, ``max_keys_per_call`` per
        transport send).  Returns the number of keys fetched — 0 is the
        warm-column steady state.  Transport-level failures never raise:
        ProviderCache.fetch degrades per key (stale / error), and an
        unknown provider lands a per-key error for every key."""
        from gatekeeper_tpu.observability import tracing

        col = self.column(provider)
        missing = col.missing(keys)
        n_req = len({k for k in keys})
        self._count_keys(provider, "warm", n_req - len(missing))
        if not missing:
            return 0
        with tracing.span("extdata.join", provider=provider,
                          n_keys=n_req, n_miss=len(missing)):
            landed: dict = {}
            for i in range(0, len(missing), self.max_keys_per_call):
                chunk = missing[i:i + self.max_keys_per_call]
                try:
                    res = self.cache.fetch(provider, chunk)
                except Exception as e:  # unknown provider etc.
                    res = {k: (None, str(e)) for k in chunk}
                landed.update(res)
                if self.metrics is not None:
                    from gatekeeper_tpu.metrics import registry as M

                    self.metrics.inc_counter(
                        M.EXTDATA_BULK_CALLS, {"provider": provider})
            landed, n_bad = validate_landed(landed)
            self._count_keys(provider, "malformed", n_bad)
            col.land(landed)
        self._count_keys(provider, "fetched", len(missing))
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.set_gauge(M.EXTDATA_COLUMN_KEYS, len(col),
                                   {"provider": provider})
        return len(missing)

    def ensure_many(self, requests: dict) -> int:
        """Land several providers' key sets concurrently: one
        :meth:`ensure` per provider, fanned across a small thread pool
        (``fanout``).  Per-key failure semantics are exactly the serial
        path's — each worker runs the unchanged ``ensure`` (bulk
        ``ProviderCache.fetch`` with per-key retry/breaker/stale
        degradation), they just overlap in wall time.  Returns total
        keys fetched (0 = every column warm)."""
        items = [(p, ks) for p, ks in sorted(requests.items()) if ks]
        if not items:
            return 0
        if len(items) == 1 or self.fanout <= 1:
            return sum(self.ensure(p, ks) for p, ks in items)
        # only cold providers pay a worker; warm ones answer inline
        cold = [(p, ks) for p, ks in items
                if self.column(p).missing(ks)]
        total = sum(self.ensure(p, ks) for p, ks in items
                    if (p, ks) not in cold)
        if not cold:
            return total
        if len(cold) == 1:
            return total + self.ensure(*cold[0])
        pool = self._pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = self._pool = ThreadPoolExecutor(
                max_workers=self.fanout,
                thread_name_prefix="extdata-fanout")
        futures = [pool.submit(self.ensure, p, ks) for p, ks in cold]
        return total + sum(f.result() for f in futures)

    # --- resolution ------------------------------------------------------
    def _resolve_perkey(self, provider: str, keys) -> dict:
        """The authoritative per-key reference: one ProviderCache.fetch
        per key (PR 2 semantics, a transport round-trip per cold key)."""
        out: dict = {}
        for k in keys:
            if k in out:
                continue
            try:
                out[k] = self.cache.fetch(provider, [k])[k]
            except Exception as e:
                out[k] = (None, str(e))
        self._count_keys(provider, "perkey", len(out))
        return out

    def _resolve_batched(self, provider: str, keys) -> dict:
        self.ensure(provider, keys)
        col = self.column(provider)
        out: dict = {}
        for k in keys:
            if k in out:
                continue
            hit = col.get(k)
            out[k] = hit if hit is not None else (None, _NOT_LANDED)
        return out

    def resolve_keys(self, provider: str, keys) -> dict:
        """``key -> (value, error-or-None)`` for deduped ``keys`` under
        the active lane mode.  ``differential`` resolves through BOTH
        paths and raises :class:`ExtDataDivergence` on any value/error
        mismatch."""
        keys = [k for k in keys]
        if self.mode == "perkey":
            return self._resolve_perkey(provider, keys)
        out = self._resolve_batched(provider, keys)
        if self.mode == "differential":
            ref = self._resolve_perkey(provider, keys)
            for k, got in out.items():
                want = ref.get(k)
                if got != want:
                    raise ExtDataDivergence(
                        f"extdata differential: provider {provider!r} "
                        f"key {k!r}: batched={got!r} perkey={want!r}")
        return out

    def resolve_placeholders(self, placeholders) -> dict:
        """Batch-resolve mutation placeholders: ONE lane resolution per
        provider over the deduped key set.  Returns
        ``(provider, key) -> (value, error-or-None)``; failure-policy
        interpretation stays with the caller (mutation/system.py), so
        Fail/Ignore/UseDefault semantics are exactly the per-key
        path's."""
        by_provider: dict = {}
        for ph in placeholders:
            by_provider.setdefault(ph.provider, []).append(ph.original_value)
        if self.mode != "perkey" and len(by_provider) > 1:
            # multi-provider burst: land every provider's misses in one
            # fan-out, then resolve from the warm columns (the perkey
            # reference keeps its strictly serial per-key transport)
            self.ensure_many(by_provider)
        out: dict = {}
        for provider, keys in sorted(by_provider.items()):
            resolved = self.resolve_keys(provider, keys)
            for k, ve in resolved.items():
                out[(provider, k)] = ve
        return out

    # --- device join tables ---------------------------------------------
    def tables_for(self, provider: str, keys, vocab) -> dict:
        """Vocab-padded join arrays for one provider after ensuring all
        ``keys`` (strings, already interned by the flatten) are landed:

        - ``ext:<provider>:ok``  bool[Vpad]  — key resolved, no per-key
          error (the ``responses`` membership test);
        - ``ext:<provider>:val`` int32[Vpad] — sid of the resolved value
          when it is a string, -2 for resolved non-string values, -3 for
          unresolved keys.

        Arrays are cached per (column version, covered vocab length), so
        a warm column returns the identical numpy objects and the
        device LRU skips the upload."""
        from gatekeeper_tpu.ir.program import _vpad

        self.ensure(provider, keys)
        col = self.column(provider)
        ver = col.version
        with self._lock:
            cached = self._table_cache.get(provider)
        if cached is not None and cached[0] == ver:
            covered = cached[1]
            if all(0 <= vocab.lookup(k) < covered for k in keys):
                return cached[2]
        covered = len(vocab)
        vp = _vpad(covered)
        ok = np.zeros(vp, bool)
        val = np.full(vp, -3, np.int32)
        for key, (v, e) in col.snapshot().items():
            sid = vocab.lookup(key)
            if not (0 <= sid < covered):
                continue  # resident key never interned: no column reads it
            if e is None:
                ok[sid] = True
                val[sid] = vocab.intern(v) if isinstance(v, str) else -2
        tables = {f"ext:{provider}:ok": ok, f"ext:{provider}:val": val}
        with self._lock:
            self._table_cache[provider] = (ver, covered, tables)
        return tables

    def snapshot(self) -> dict:
        """Introspection (tests / debug): per-provider residency."""
        with self._lock:
            cols = dict(self._columns)
        return {
            "mode": self.mode,
            "providers": {p: {"keys": len(c), "version": c.version}
                          for p, c in sorted(cols.items())},
        }


# --- activation (mirrors resilience/faults.py) ----------------------------

_ctx_lane: contextvars.ContextVar = contextvars.ContextVar(
    "extdata_lane", default=None)
_global_lane: list = [None]


def install(lane: Optional[ExtDataLane]) -> None:
    """Process-global activation (the ``--extdata-lane`` CLI path):
    webhook handler threads, the audit thread and the batcher all see
    one lane."""
    _global_lane[0] = lane


def uninstall() -> None:
    _global_lane[0] = None


@contextmanager
def activate(lane: ExtDataLane, process: bool = True):
    """Scoped activation for tests; restores both scopes on exit."""
    token = _ctx_lane.set(lane)
    prev = _global_lane[0]
    if process:
        _global_lane[0] = lane
    try:
        yield lane
    finally:
        _ctx_lane.reset(token)
        if process:
            _global_lane[0] = prev


def active() -> Optional[ExtDataLane]:
    lane = _ctx_lane.get()
    if lane is None:
        lane = _global_lane[0]
    return lane


# --- the Rego builtin's fetch (lang/rego/builtins.py delegates here) ------

def builtin_fetch(req):
    """``external_data({"provider": p, "keys": [...]})`` — the reference
    response shape: ``{"responses": [[key, value], ...], "errors":
    [[key, err], ...], "status_code": 200, "system_error": ""}``.

    Transport-level failures surface as PER-KEY errors (the
    ProviderCache stale/error fallback), never as ``system_error`` —
    the lowered device join and this host reference agree on that
    single encoding.  Keys dedupe on first occurrence; non-string keys
    are per-key errors (the device join's non-string subjects read
    not-resolved the same way).  With no lane active every key errors —
    external-data policies fail closed toward their template's own
    error handling."""
    from gatekeeper_tpu.lang.rego.builtins import UNDEFINED

    if not isinstance(req, dict):
        return UNDEFINED
    provider = req.get("provider")
    keys = req.get("keys")
    if not isinstance(provider, str) or not isinstance(keys, list):
        return UNDEFINED
    uniq: list = []
    seen: set = set()
    for k in keys:
        marker = k if isinstance(k, (str, int, float, bool)) else repr(k)
        if (type(marker), marker) in seen:
            continue
        seen.add((type(marker), marker))
        uniq.append(k)
    str_keys = [k for k in uniq if isinstance(k, str)]
    lane = active()
    if lane is None:
        resolved = {k: (None, "external data: no lane configured")
                    for k in str_keys}
    else:
        resolved = lane.resolve_keys(provider, str_keys)
    responses: list = []
    errors: list = []
    for k in uniq:
        if not isinstance(k, str):
            errors.append([k, "external data: key is not a string"])
            continue
        v, e = resolved.get(k, (None, _NOT_LANDED))
        if e:
            errors.append([k, e])
        else:
            responses.append([k, v])
    return {"responses": responses, "errors": errors,
            "status_code": 200, "system_error": ""}
