"""Process entry: run the framework against a manifest directory.

Reference: main.go — two deployment shapes share one binary, split by
--operation (audit pod vs controller-manager/webhook pod,
deploy/gatekeeper.yaml:5744,5852).  This entry reconciles manifests from
--manifests into the systems, then serves the webhook and/or runs the audit
loop:

    python -m gatekeeper_tpu --manifests ./manifests \
        --operation webhook --operation audit --port 8443
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gatekeeper-tpu")
    p.add_argument("--manifests", action="append", default=[],
                   help="directory/file of templates, constraints, config, "
                        "mutators, data objects")
    p.add_argument("--kubeconfig", default="",
                   help="run against a live Kubernetes apiserver (watch + "
                        "paged list informer plane); 'in-cluster' uses the "
                        "service-account environment")
    p.add_argument("--evaluate-sidecar", default="",
                   help="host:port of a device-owning Evaluate sidecar "
                        "(python -m gatekeeper_tpu.rpc.sidecar); this "
                        "process then runs the control plane only — no "
                        "local accelerator")
    p.add_argument("--operation", action="append", default=[],
                   help="audit|webhook|mutation-webhook (repeatable; "
                        "default all)")
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--readiness-retries", type=int, default=0,
                   help="ingestion attempts allowed before a failing "
                        "resource's readiness expectation is cancelled; "
                        "-1 retries indefinitely (reference "
                        "--readiness-retries, object_tracker.go:36)")
    p.add_argument("--audit-interval", type=float, default=60.0)
    p.add_argument("--constraint-violations-limit", type=int, default=20)
    p.add_argument("--audit-chunk-size", type=int, default=500)
    p.add_argument("--audit-source", default="relist",
                   choices=["relist", "snapshot"],
                   help="sweep input: 'relist' pages the cluster every "
                        "pass; 'snapshot' keeps the flattened columns "
                        "RESIDENT between sweeps, maintained by the "
                        "watch seam — a full pass evaluates resident "
                        "columns (no list/flatten cost) and interval "
                        "ticks evaluate only the watch-dirtied rows "
                        "(O(churn)); a periodic full-resync "
                        "differential asserts snapshot == fresh relist "
                        "bit-identical (README 'Incremental audit & "
                        "snapshot')")
    p.add_argument("--snapshot-resync-every", type=int, default=10,
                   help="snapshot mode: every Nth audit interval runs "
                        "the full-resync differential instead of an "
                        "incremental tick (0 = never); divergence "
                        "marks the run incomplete and rebuilds the "
                        "snapshot")
    p.add_argument("--snapshot-resync-rotate", type=int, default=0,
                   help="rotate the resync differential over 1/K of "
                        "the keyspace per resync interval: each resync "
                        "re-flattens only its deterministic key-hash "
                        "slice, so the bit-identity proof amortizes "
                        "(K consecutive resyncs cover every row) "
                        "instead of re-flattening the whole cluster in "
                        "one generation; 0/1 = off (one-shot full "
                        "differential incl. the cluster-global verdict "
                        "check)")
    p.add_argument("--snapshot-spill", default="",
                   help="snapshot mode: directory for the on-disk spill "
                        "of the resident audit state (tall columns + "
                        "vocab + row ids + verdicts + per-GVK rv marks). "
                        "On boot a valid spill warm-starts the auditor — "
                        "watches resubscribe FROM the recorded rv and "
                        "the first tick pays zero relist and zero "
                        "flatten; a corrupt or drifted spill is deleted "
                        "and the boot relists (README 'Cold start & "
                        "persistence').  Spills write off the audit "
                        "thread after each clean resync and at drain")
    p.add_argument("--snapshot-spill-compress", default="none",
                   choices=["none", "zlib"],
                   help="spill section codec: 'none' (bit-identical to "
                        "the uncompressed format — right for 1-core "
                        "hosts, where zlib CPU costs more than the "
                        "bytes) or 'zlib' (NVMe-rich hosts: ~3-5x "
                        "smaller sections for one compress pass on the "
                        "spill worker).  The header records the codec; "
                        "the loader auto-detects either, so flipping "
                        "the flag never strands an existing spill")
    p.add_argument("--snapshot-spill-delta", action="store_true",
                   help="incremental spills: groups split into per-group "
                        "section files and a spill rewrites ONLY the "
                        "groups whose mutation mark moved since the last "
                        "write — O(churn) disk instead of O(cluster). "
                        "Every --snapshot-spill-full-every'th spill is a "
                        "full rewrite that prunes orphaned group files "
                        "(the compaction path); off keeps the inline "
                        "single-section format byte-identical")
    p.add_argument("--snapshot-spill-full-every", type=int, default=8,
                   help="delta spills: force a full rewrite (and orphan "
                        "prune) every Nth spill (default 8)")
    p.add_argument("--snapshot-residency", default="auto",
                   choices=["auto", "on", "off"],
                   help="device-resident snapshot columns: keep each "
                        "group's tall packed columns + match masks in "
                        "device HBM, apply watch patches as device "
                        "scatter from dirty-row slivers, and dispatch "
                        "audit chunks as an index gather — a warm clean "
                        "tick uploads ZERO bytes (README 'Device-"
                        "resident snapshot').  'auto' promotes only when "
                        "an accelerator backs the mesh (CPU hosts keep "
                        "host columns, logged once); 'on' forces "
                        "promotion (the CPU differential shape); 'off' "
                        "disables the lane.  The built-in "
                        "device_residency_evict degradation action "
                        "demotes resident groups on SLO breach")
    p.add_argument("--audit-expand", action="store_true",
                   help="expansion generator stage in the audit sweep: "
                        "generator objects (per ExpansionTemplate "
                        "applyTo) expand through the batched mutlane "
                        "stage and their resultants — implied Pods with "
                        "Source=Generated mutation applied — are audited "
                        "at sweep scale with the template's "
                        "enforcementAction override (README 'Batched "
                        "mutation & expansion')")
    p.add_argument("--fleet-config", default="",
                   help="fleet mode: JSON roster of clusters "
                        "({'clusters': [{'id': ..., 'manifests': "
                        "[...]}]}) — one process multiplexes every "
                        "cluster's audit plane behind SHARED per-library "
                        "runtimes (clusters running the same template "
                        "library share compiled executables; a second "
                        "same-library cluster boots with zero lowering) "
                        "and the fleet sweep packs small clusters' "
                        "same-group chunks into device-sized dispatches. "
                        "Honors --compile-cache (one shared cache), "
                        "--snapshot-spill (per-cluster subdirs), "
                        "--audit-interval/--audit-chunk-size/--once "
                        "(README 'Fleet mode')")
    p.add_argument("--mutate-ingest", default="dict",
                   choices=["dict", "raw", "differential"],
                   help="/v1/mutate burst columnizer: 'dict' keeps the "
                        "dict-walk lane byte-for-byte; 'raw' serializes "
                        "each burst once and feeds the PR 4 raw-bytes "
                        "threaded C columnizer (GIL released) — match "
                        "walks and patch emission still read the dict "
                        "objects, so outcomes are lane-invariant; "
                        "'differential' runs raw THEN dict per batch "
                        "and asserts the columns bit-identical")
    p.add_argument("--mutate-lane", default="batched",
                   choices=["batched", "host", "differential"],
                   help="/v1/mutate serving lane: 'batched' coalesces "
                        "mutate reviews into one columnar lane pass "
                        "(host fixed-point fallback for unsupported "
                        "mutators); 'host' is the per-object reference "
                        "path; 'differential' runs the batched lane AND "
                        "asserts it bit-identical to the reference per "
                        "batch (debugging)")
    p.add_argument("--pipeline", default="auto",
                   choices=["auto", "on", "off", "differential"],
                   help="audit sweep schedule: 'auto' runs the staged "
                        "host pipeline (list->flatten->dispatch->collect"
                        "->fold on separate threads, bounded queues) when "
                        "the host has >1 effective core and degrades to "
                        "the serial eager-poll schedule otherwise; "
                        "'on'/'off' force; 'differential' runs both and "
                        "asserts bit-identical output (debugging)")
    p.add_argument("--pipeline-flatten-workers", type=int, default=0,
                   help="threads in the pipeline's flatten stage; 0 = "
                        "auto (2 when the host has >=4 effective cores). "
                        "The C columnizer already shards each chunk over "
                        "an internal pthread pool; extra workers overlap "
                        "the Python assembly slices across chunks")
    p.add_argument("--flatten-workers", type=int, default=0,
                   help="multiprocess flatten worker pool for sweep "
                        "chunks: fan contiguous RawJSON byte spans of "
                        "each chunk across N worker PROCESSES (each runs "
                        "the C columnizer against a batch-local vocab; "
                        "results merge into the shared vocab on the "
                        "dispatch thread, bit-identical to in-process — "
                        "see ops/flatten.FlattenWorkerPool). 0 = the "
                        "exact in-process path (the 1-core default); "
                        "with --flatten-lane differential the worker "
                        "pool is additionally asserted column- and "
                        "vocab-identical per chunk")
    p.add_argument("--shard-chunks", type=int, default=0,
                   help="pack K consecutive same-group audit chunks "
                        "into one mesh-wide dispatch (object axis "
                        "sharded over the mesh 'data' axis) — K ~= "
                        "device count keeps each chip at "
                        "audit-chunk-size objects while per-dispatch "
                        "fixed costs amortize K-fold; 0/1 = off")
    p.add_argument("--flatten-lane", default="auto",
                   choices=["auto", "dict", "raw", "py", "differential"],
                   help="sweep columnizer lane: 'auto' feeds raw JSON "
                        "bytes from the lister straight through the "
                        "threaded C columnizer when available (falling "
                        "back to the GIL-bound dict walker, then "
                        "Python); 'raw'/'dict'/'py' force a lane; "
                        "'differential' runs raw THEN dict per chunk "
                        "and asserts bit-identical columns (debugging)")
    p.add_argument("--extdata-lane", default="batched",
                   choices=["batched", "perkey", "differential"],
                   help="external-data resolution lane: 'batched' dedupes "
                        "provider keys across each admission burst / audit "
                        "chunk, bulk-fetches per provider into resident "
                        "columns and joins verdicts on device; 'perkey' "
                        "keeps the per-key ProviderCache reference path "
                        "(external-data templates stay on the exact "
                        "interpreter); 'differential' runs batched AND "
                        "asserts verdicts + resolved values bit-identical "
                        "to per-key")
    p.add_argument("--extdata-max-keys", type=int, default=256,
                   help="max keys per bulk provider call (the batched "
                        "lane chunks larger deduped miss lists into "
                        "multiple transport sends)")
    p.add_argument("--extdata-fanout", type=int, default=4,
                   help="per-provider concurrency of the batched lane's "
                        "bulk fetches: a chunk referencing N providers "
                        "lands their miss lists across this many threads "
                        "(1 = strictly serial, the pre-fanout behavior)")
    p.add_argument("--generation-swap", default="on",
                   choices=["on", "off"],
                   help="template-churn compile lane: 'on' stages "
                        "post-boot template/constraint mutations, "
                        "compiles the next generation on a background "
                        "thread and atomically swaps executables in "
                        "(the serving path never pays lowering); 'off' "
                        "compiles inline on the reconcile path, "
                        "bit-identical to the pre-generation behavior. "
                        "Boot (manifests + warm) is always synchronous")
    p.add_argument("--compile-cache", default="",
                   help="directory for the on-disk compile cache: "
                        "lowered template programs keyed by (template "
                        "digest, engine, jax/jaxlib version, "
                        "flatten-schema version) with a vocab snapshot "
                        "replay, plus JAX's persistent XLA compilation "
                        "cache under <dir>/xla — a warm restart or "
                        "--once run skips lowering entirely")
    p.add_argument("--collect", default="reduced",
                   choices=["reduced", "masks", "differential"],
                   help="sweep collect lane: 'reduced' folds verdicts ON "
                        "DEVICE (per-constraint totals + top-k kept "
                        "selection + mask occupancy in one small packed "
                        "transfer — O(kept) device->host bytes, not "
                        "O(objects x constraints)); 'masks' ships the "
                        "bit grid and folds on the host (the reference "
                        "lane); 'differential' runs both per chunk and "
                        "asserts totals/kept/occupancy bit-identical")
    p.add_argument("--export-dir", default="",
                   help="enable disk export of audit violations")
    p.add_argument("--log-denies", action="store_true",
                   help="log structured deny events (reference --log-denies)")
    p.add_argument("--emit-admission-events", action="store_true",
                   help="emit K8s Events on admission violations "
                        "(reference --emit-admission-events)")
    p.add_argument("--admission-events-involved-namespace",
                   action="store_true",
                   help="emit admission Events in the violating object's "
                        "namespace instead of the gatekeeper namespace")
    p.add_argument("--emit-audit-events", action="store_true",
                   help="emit K8s Events on audit violations "
                        "(reference --emit-audit-events)")
    p.add_argument("--audit-events-involved-namespace",
                   action="store_true",
                   help="emit audit Events in the violating object's "
                        "namespace instead of the gatekeeper namespace")
    p.add_argument("--gatekeeper-namespace", default="gatekeeper-system",
                   help="namespace Events land in by default")
    p.add_argument("--log-stats-admission", action="store_true",
                   help="log per-request evaluation stats (reference "
                        "--log-stats-admission)")
    p.add_argument("--certs-dir", default="",
                   help="serve TLS using (or generating) certs in this dir")
    p.add_argument("--client-ca-file", default="",
                   help="require and verify client certificates against "
                        "this CA (reference --client-ca-name)")
    p.add_argument("--tls-min-version", default="1.3",
                   choices=["1.2", "1.3"])
    p.add_argument("--shutdown-delay", type=float, default=0.0,
                   help="seconds to keep serving after SIGTERM before "
                        "shutting down (reference --shutdown-delay); "
                        "readiness answers 503 {draining:true} for the "
                        "whole window so the LB deregisters first")
    p.add_argument("--drain-timeout", type=float, default=10.0,
                   help="graceful-drain budget in seconds: after SIGTERM "
                        "(and --shutdown-delay) the listener stops "
                        "accepting and in-flight admissions + the "
                        "batcher queue drain to completion within this "
                        "budget — zero accepted verdicts lost")
    p.add_argument("--webhook-backlog", type=int, default=128,
                   help="kernel listen(2) accept-queue depth for the "
                        "webhook socket (unanswered TCP connects. "
                        "Distinct from the overload limiter's cost-aware "
                        "admission queue, which holds ACCEPTED requests "
                        "waiting for a review slot — see README "
                        "'Overload & drain semantics')")
    p.add_argument("--overload-limiter", default="on",
                   choices=["on", "off"],
                   help="adaptive-concurrency admission gate in front of "
                        "the validating webhook (AIMD on review latency "
                        "vs a baseline EWMA + bounded cost-aware queue); "
                        "'on' is bit-identical to 'off' while unloaded "
                        "(differential-tested); sheds resolve per "
                        "--webhook-failure-policy")
    p.add_argument("--overload-max-inflight", type=int, default=64,
                   help="upper bound of the adaptive in-flight limit")
    p.add_argument("--overload-queue-depth", type=int, default=256,
                   help="max requests waiting in the admission queue "
                        "before sheds begin")
    p.add_argument("--overload-queue-cost", type=float, default=256e6,
                   help="max summed admission cost (object bytes x "
                        "matched-constraint estimate) queued before "
                        "sheds begin")
    p.add_argument("--qos", default="off", choices=["on", "off"],
                   help="per-tenant / per-priority admission QoS on the "
                        "overload path: priority lanes (system / "
                        "break-glass ahead of user traffic, shed last), "
                        "weighted-fair (deficit-round-robin) dequeue "
                        "across tenants, per-tenant inflight caps + "
                        "queue-cost budgets, and tenant-aware "
                        "displacement (the heaviest tenant sheds "
                        "first).  'off' (the compat default) keeps the "
                        "single cost-aware FIFO bit-identical to "
                        "previous releases (README 'Tenant QoS & "
                        "fairness')")
    p.add_argument("--qos-config", default="",
                   help="JSON file of QoS priority levels / tenant "
                        "weights / caps, mirroring the apiserver APF "
                        "PriorityLevel shape (see README 'Tenant QoS & "
                        "fairness'); empty = the built-in lane set "
                        "(kube-system + gatekeeper-system + system: "
                        "users ahead of break-glass ahead of everyone, "
                        "namespace as the tenant key)")
    p.add_argument("--qos-ledger-decay", default="events",
                   choices=["events", "slo-window"],
                   help="decay driver for the QoS displacement ledger "
                        "(who is 'heaviest'): 'events' (the default) "
                        "halves totals per fixed charge count — "
                        "deterministic replay; 'slo-window' halves them "
                        "per elapsed SLO short-window on the SLO "
                        "engine's clock, so tenant heaviness ages on "
                        "the same timebase the burn-rate windows use "
                        "(an idle gap forgets a past burst)")
    p.add_argument("--enable-profile", action="store_true",
                   help="serve /debug/profile?seconds=N (pprof equivalent)")
    p.add_argument("--fail-open-on-error", action="store_true",
                   help="admit (with a warning) when the review path raises "
                        "internally, instead of the reference's Errored "
                        "allowed=false code-500 response")
    p.add_argument("--exempt-namespace", action="append", default=[],
                   help="namespace allowed to set the ignore label "
                        "(repeatable; reference --exempt-namespace)")
    p.add_argument("--exempt-namespace-prefix", action="append", default=[],
                   help="namespace name prefix allowed to set the ignore "
                        "label (repeatable)")
    p.add_argument("--exempt-namespace-suffix", action="append", default=[],
                   help="namespace name suffix allowed to set the ignore "
                        "label (repeatable)")
    p.add_argument("--cert-rotation-check-s", type=float, default=3600.0,
                   help="cert expiry check interval for the rotation loop")
    p.add_argument("--management-manifests", default="",
                   help="remote-cluster mode: status/secret state routes to "
                        "a separate management cluster seeded from this "
                        "directory (reference --enable-remote-cluster)")
    p.add_argument("--coordinator", default="",
                   help="multi-host: coordinator address host:port "
                        "(joins a global JAX mesh across processes)")
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=0)
    p.add_argument("--once", action="store_true",
                   help="run one audit sweep and exit (no servers)")
    p.add_argument("--webhook-small-batch", type=int, default=None,
                   help="admission batches this size or smaller take the "
                        "per-review interpreter lane instead of the "
                        "device verdict grid (default 8 — the measured "
                        "grid-launch crossover; the lanes agree "
                        "bit-for-bit)")
    p.add_argument("--chaos", default="",
                   help="fault-injection spec (JSON file: {\"seed\": 0, "
                        "\"faults\": [{\"site\": ..., \"mode\": sleep|"
                        "hang|error|partial, ...}]}) installed process-"
                        "wide — the deterministic chaos harness for "
                        "exercising the resilience layer (README "
                        "'Failure semantics')")
    p.add_argument("--trace", default="",
                   help="write a Chrome trace-event JSON (Perfetto-"
                        "loadable) of the kept traces to this path on "
                        "exit; also serves the live tail-sampled ring "
                        "buffer at /debug/traces next to /metrics")
    p.add_argument("--trace-buffer", action="store_true",
                   help="enable the span tracer without a file export "
                        "(ring buffer served at /debug/traces only)")
    p.add_argument("--trace-slow-ms", type=float, default=0.0,
                   help="tail-sampling latency threshold: traces whose "
                        "root span is slower than this are ALWAYS kept; "
                        "the rest keep at --trace-sample (0 = no "
                        "threshold, keep per --trace-sample alone)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="keep probability for traces under the "
                        "--trace-slow-ms threshold (1.0 keeps all; 0.0 "
                        "is the empty sampler — span machinery runs, "
                        "nothing retained)")
    p.add_argument("--trace-seed", type=int, default=None,
                   help="seed the trace/span ID generator and sampler "
                        "(deterministic IDs for differential runs; "
                        "default: OS entropy)")
    p.add_argument("--cost-attribution", default="on",
                   choices=["on", "off"],
                   help="per-template cost attribution: shared device "
                        "passes apportion wall time across the "
                        "constraint grid by row occupancy "
                        "(gatekeeper_constraint_eval_seconds, "
                        "/debug/cost, `gator bench --attribution`)")
    p.add_argument("--slo", default="on", choices=["on", "off"],
                   help="in-process SLO engine: declarative objectives "
                        "(admission/mutate P99, shed rate, audit "
                        "staleness) with multi-window burn rates — "
                        "gatekeeper_slo_* gauges, /debug/slo, breach "
                        "span events")
    p.add_argument("--slo-config", default="",
                   help="JSON file of SLO objectives (and optional burn "
                        "tiers) replacing the built-in defaults — see "
                        "README 'Observability' for the format")
    p.add_argument("--slo-interval", type=float, default=10.0,
                   help="seconds between SLO engine evaluations")
    p.add_argument("--slo-brownout", action="store_true",
                   help="feed SLO burn into the overload brownout "
                        "ladder: a burning latency objective browns out "
                        "optional work (stale lookups, audit device-"
                        "lane yield) BEFORE the admission queue backs "
                        "up (off keeps the ladder queue-driven only)")
    p.add_argument("--slo-degradation", default="off",
                   choices=["on", "off"],
                   help="targeted degradation maps: each objective's "
                        "ordered, revocable action list (ns_cache_stale "
                        "-> extdata_stale -> shed_harder; "
                        "audit_yield_release -> resync_defer) activates "
                        "step-by-step on burn breach and releases in "
                        "reverse on recovery — the surgical alternative "
                        "to the scalar --slo-brownout ladder (both can "
                        "run together)")
    p.add_argument("--flight-recorder", type=int, default=2048,
                   help="admission flight recorder: ring capacity of "
                        "structured admission/mutation/shed decision "
                        "records served at /debug/decisions?uid= "
                        "(0 disables)")
    p.add_argument("--flight-recorder-sink", default="",
                   help="append every flight-recorder decision to this "
                        "JSONL file (the operator's black box; decision "
                        "metadata only, never object bodies — unless "
                        "--flight-recorder-capture)")
    p.add_argument("--flight-recorder-sink-max-mb", type=float,
                   default=0.0,
                   help="rotate the sink when it reaches this many MB "
                        "(sink -> sink.1 -> sink.2 ...; 0 = unbounded). "
                        "gator decisions/triage read rotated sets "
                        "transparently")
    p.add_argument("--flight-recorder-sink-keep", type=int, default=3,
                   help="rotated sink files retained past the live one "
                        "(oldest dropped on rotation)")
    p.add_argument("--flight-recorder-capture", action="store_true",
                   help="capture mode: sink lines additionally carry "
                        "the raw admission request (the `gator replay` "
                        "corpus). The in-memory ring stays metadata-"
                        "only; the sink then holds Secrets-grade data")
    p.add_argument("--shadow-candidate", action="append", default=[],
                   help="shadow canary: candidate library file/dir "
                        "(repeatable). Copies of live admissions "
                        "evaluate against it off the response path — "
                        "verdicts are never answered; /debug/shadow, "
                        "gatekeeper_shadow_* metrics, and the shadow-"
                        "divergence-rate SLO objective carry the "
                        "promote/abort signal")
    p.add_argument("--shadow-sink", default="",
                   help="append shadow verdicts to this JSONL file "
                        "(the shadow flight-recorder stream)")
    p.add_argument("--webhook-deadline", type=float, default=0.0,
                   help="per-admission wall-clock budget in seconds; on "
                        "expiry the request resolves per "
                        "--webhook-failure-policy instead of stalling "
                        "the apiserver (0 disables)")
    p.add_argument("--webhook-failure-policy", default="fail",
                   choices=["ignore", "fail"],
                   help="what a failed/timed-out review answers: "
                        "'ignore' fails open (allow + warning "
                        "annotation), 'fail' fails closed (deny with "
                        "reason) — the reference webhook failurePolicy")
    p.add_argument("--webhook-workers", type=int, default=1,
                   help="serve the webhook from N processes sharing one "
                        "port via SO_REUSEPORT (the kernel load-balances "
                        "connections; each worker is a full replica of "
                        "the serving stack).  The multi-core answer to "
                        "the reference's goroutine-per-request model "
                        "(policy.go:116-120)")
    p.add_argument("--reuse-port", action="store_true",
                   help="bind the webhook port with SO_REUSEPORT (set "
                        "automatically for --webhook-workers children)")
    args = p.parse_args(argv)

    if args.fleet_config:
        # fleet mode is its own process shape (N clusters' audit planes
        # behind shared runtimes) — the single-cluster wiring below
        # does not apply
        from gatekeeper_tpu.fleet.run import run_fleet

        return run_fleet(args)

    worker_procs: list = []
    if args.webhook_workers > 1 and args.once:
        print("--webhook-workers ignored with --once (no servers run)",
              file=sys.stderr)
        args.webhook_workers = 1
    if args.webhook_workers > 1:
        # documented gate (VERDICT r4 weak #5 / WEBHOOK_LOAD.json
        # multiworker2): on hosts with fewer effective cores than
        # workers, SO_REUSEPORT processes convoy on the CPU — measured
        # 36x P99 blowup on one core.  Serve multi-worker only when each
        # worker can actually get a core.
        from gatekeeper_tpu.pipeline import effective_cpu_count

        cores = effective_cpu_count()
        if cores < args.webhook_workers:
            print(f"WARNING: --webhook-workers {args.webhook_workers} on "
                  f"a {cores}-core host: workers will convoy on the CPU "
                  f"(measured 36x P99 inflation on one core — see README "
                  f"'Failure semantics'); use at most {max(1, cores)} "
                  f"workers here", file=sys.stderr)
        if args.port == 0:
            p.error("--webhook-workers needs an explicit --port "
                    "(ephemeral ports cannot be shared)")
        if args.certs_dir:
            # generate serving certs BEFORE spawning workers: N processes
            # racing first-boot generation would overwrite each other's
            # key/cert pairs (mismatched tls.crt/tls.key)
            import os

            from gatekeeper_tpu.webhook.certs import generate_certs

            if not os.path.exists(os.path.join(args.certs_dir, "tls.crt")):
                generate_certs(args.certs_dir)
        import subprocess

        child_argv = list(argv) if argv is not None else sys.argv[1:]
        # strip the workers flag (children must not fork grandchildren)
        # and the parent's --operation set (children serve webhooks ONLY
        # — exactly one audit/controller process per --operation split,
        # as in the reference Deployment)
        stripped: list = []
        skip = False
        for a in child_argv:
            if skip:
                skip = False
                continue
            if a in ("--webhook-workers", "--operation", "--trace"):
                # --trace: N workers would race the export-file write at
                # exit; only the parent writes the artifact
                skip = True
                continue
            if a.startswith(("--webhook-workers=", "--operation=",
                             "--trace=")):
                continue
            stripped.append(a)
        child = [a for a in stripped if a != "--once"]
        child += ["--reuse-port", "--operation", "webhook",
                  "--operation", "mutation-webhook",
                  # only the parent runs cert rotation: N concurrent
                  # renewals would interleave generate_certs writes into
                  # mismatched tls.crt/tls.key pairs
                  "--cert-rotation-check-s", "0"]
        for i in range(args.webhook_workers - 1):
            worker_procs.append(subprocess.Popen(
                [sys.executable, "-m", "gatekeeper_tpu"] + child))
        args.reuse_port = True

    if args.coordinator:
        from gatekeeper_tpu.parallel.distributed import init_distributed

        init_distributed(args.coordinator, args.num_processes,
                         args.process_id)
        print(f"joined global mesh: process {args.process_id}/"
              f"{args.num_processes}", file=sys.stderr)

    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.controller.manager import ALL_OPERATIONS, Manager
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.export.system import ExportSystem
    from gatekeeper_tpu.gator import reader
    from gatekeeper_tpu.metrics.registry import MetricsRegistry
    from gatekeeper_tpu.sync.source import FakeCluster, FileSource
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.webhook.mutation import MutationHandler
    from gatekeeper_tpu.webhook.namespacelabel import NamespaceLabelHandler
    from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
    from gatekeeper_tpu.webhook.server import WebhookServer

    operations = args.operation or list(ALL_OPERATIONS)
    metrics = MetricsRegistry()
    tracer = None
    if args.trace or args.trace_buffer:
        from gatekeeper_tpu.observability import tracing

        tracer = tracing.Tracer(
            seed=args.trace_seed,
            slow_threshold_s=(args.trace_slow_ms / 1000.0
                              if args.trace_slow_ms > 0 else None),
            sample_rate=args.trace_sample,
            metrics=metrics,
        )
        tracing.install(tracer)
        print("span tracer active"
              + (f" (export: {args.trace})" if args.trace else
                 " (ring buffer at /debug/traces)"), file=sys.stderr)
    if args.chaos:
        from gatekeeper_tpu.resilience import faults

        faults.set_metrics_registry(metrics)
        faults.install(faults.load_chaos_spec(args.chaos))
        print(f"chaos harness active: {args.chaos}", file=sys.stderr)
    # overload protection + graceful drain (resilience/overload.py):
    # the drain coordinator always exists (SIGTERM drives it); the
    # adaptive limiter gates the validating webhook when enabled —
    # installed process-wide so the brownout ladder reaches the
    # externaldata cache and the audit sweep's device-lane yield
    from gatekeeper_tpu.resilience import overload as _overload

    drain = _overload.DrainCoordinator(metrics=metrics)
    overload_ctl = None
    if args.overload_limiter == "on" and not args.once:
        from gatekeeper_tpu.resilience.qos import qos_from_args

        qos_cfg = qos_from_args(args.qos, args.qos_config)
        overload_ctl = _overload.OverloadController(
            _overload.OverloadConfig(
                max_inflight=args.overload_max_inflight,
                queue_depth=args.overload_queue_depth,
                queue_cost=args.overload_queue_cost,
                qos=qos_cfg,
            ),
            metrics=metrics)
        _overload.install(overload_ctl)
        if qos_cfg is not None:
            print(f"admission QoS active: "
                  f"{len(qos_cfg.levels)} priority lanes "
                  f"({', '.join(lv.name for lv in qos_cfg.levels)}), "
                  f"tenant key {qos_cfg.tenant_key}, "
                  f"inflight cap {qos_cfg.tenant_inflight_cap or 'none'} "
                  f"(/debug/overload)", file=sys.stderr)
    # the L6 observability trio (README "Observability"): cost
    # attribution + SLO engine + flight recorder, all metric-registry
    # backed and served from the /debug endpoints next to /metrics
    from gatekeeper_tpu.observability import costattr as _costattr
    from gatekeeper_tpu.observability import flightrec as _flightrec
    from gatekeeper_tpu.observability import slo as _slo

    cost_attr = None
    if args.cost_attribution == "on":
        cost_attr = _costattr.CostAttribution(metrics=metrics)
        _costattr.install(cost_attr)
        if overload_ctl is not None and args.qos == "on":
            # the {tenant} axis feeds QoS displacement: measured
            # per-tenant eval cost decides who is "heaviest", not
            # arrival order
            overload_ctl.set_tenant_cost_input(cost_attr.tenant_totals)
    flight_rec = None
    if args.flight_recorder > 0 and not args.once:
        flight_rec = _flightrec.FlightRecorder(
            capacity=args.flight_recorder,
            sink_path=args.flight_recorder_sink or None,
            metrics=metrics,
            capture=args.flight_recorder_capture,
            sink_max_bytes=int(args.flight_recorder_sink_max_mb
                               * 1024 * 1024),
            sink_keep=args.flight_recorder_sink_keep)
        _flightrec.install(flight_rec)
    slo_engine = None
    if args.slo == "on" and not args.once:
        degradations = None
        if args.slo_degradation == "on":
            # targeted per-objective degradation maps: the registry the
            # overload controller / ProviderCache / AuditManager consult
            # (degradation_active) and the engine drives edges into
            degradations = _overload.DegradationRegistry(metrics=metrics)
            _overload.install_degradations(degradations)
        slo_kw: dict = {"degradations": degradations}
        if args.slo_config:
            try:
                cfg = _slo.load_config(args.slo_config, degradations)
            except _slo.SLOConfigError as e:
                # fail fast at boot: a malformed objective silently
                # dropped is an SLO that never pages
                print(f"slo config: {e}", file=sys.stderr)
                return 2
            slo_kw["objectives"] = cfg["objectives"]
            if cfg["tiers"]:
                slo_kw["tiers"] = cfg["tiers"]
        elif args.shadow_candidate:
            # shadow canary on: the divergence-rate objective rides the
            # default set (an explicit --slo-config replaces defaults
            # wholesale, shadow objective included, like everything else)
            from gatekeeper_tpu.replay.shadow import SHADOW_OBJECTIVE

            slo_kw["objectives"] = (list(_slo.DEFAULT_OBJECTIVES)
                                    + [SHADOW_OBJECTIVE])
        slo_engine = _slo.SLOEngine(metrics, brownout=overload_ctl,
                                    **slo_kw)
        if args.slo_brownout and overload_ctl is not None:
            overload_ctl.set_slo_input(slo_engine.pressure)
        slo_engine.start(interval_s=args.slo_interval)
        print(f"SLO engine active: "
              f"{len(slo_engine.objectives)} objectives, tick every "
              f"{args.slo_interval:.0f}s (/debug/slo)"
              + (", degradation maps armed"
                 if degradations is not None else ""), file=sys.stderr)
    if args.qos == "on" and args.qos_ledger_decay == "slo-window" \
            and overload_ctl is not None:
        # displacement-ledger decay on the SLO window clock (default
        # 'events' keeps the deterministic event-count decay untouched)
        if slo_engine is not None:
            overload_ctl.set_qos_ledger_clock(
                slo_engine.window_clock, slo_engine.shortest_window_s())
        else:
            overload_ctl.set_qos_ledger_clock(time.monotonic, 300.0)
        print("qos ledger decay: slo-window", file=sys.stderr)
    cel = CELDriver()
    if args.evaluate_sidecar:
        from gatekeeper_tpu.drivers.remote import RemoteDriver

        tpu = RemoteDriver(args.evaluate_sidecar)
        # the sidecar container may still be initializing its devices:
        # wait for channel readiness instead of crash-looping on a race
        import grpc as _grpc

        try:
            _grpc.channel_ready_future(tpu._channel).result(timeout=120)
            print(f"evaluation plane: sidecar {args.evaluate_sidecar} "
                  f"({tpu.dump()['sidecar']})", file=sys.stderr)
        except Exception as e:
            print(f"evaluate sidecar unreachable after 120s: {e}",
                  file=sys.stderr)
            return 1
    else:
        compile_cache = None
        if args.compile_cache:
            from gatekeeper_tpu.drivers.generation import CompileCache

            compile_cache = CompileCache(args.compile_cache,
                                         metrics=metrics)
            try:
                # XLA executables persist beside the lowering entries;
                # min thresholds dropped so small admission kernels cache
                import jax as _jax

                _jax.config.update("jax_compilation_cache_dir",
                                   compile_cache.xla_cache_dir())
                _jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
                _jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0)
            except Exception as e:
                print(f"xla compile cache unavailable: {e}",
                      file=sys.stderr)
            print(f"compile cache: {args.compile_cache}", file=sys.stderr)
        tpu = TpuDriver(cel_driver=cel, metrics=metrics,
                        generation_swap=args.generation_swap == "on",
                        compile_cache=compile_cache)
    client = Client(target=K8sValidationTarget(),
                    drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, "audit.gatekeeper.sh"])
    if getattr(tpu, "gen_coord", None) is not None:
        # pre-swap warm traces changed kernels at the real serving shape
        tpu.gen_coord.constraints_fn = client.constraints
    shadow_lane = None
    if args.shadow_candidate and not args.once:
        # continuous shadow canary (replay/shadow.py): the candidate
        # library loads through the same on-disk compile cache as
        # serving, so a warmed candidate attaches with zero fresh
        # lowerings; the webhook's per-decision hook feeds the lane
        from gatekeeper_tpu.gator import reader as _reader
        from gatekeeper_tpu.replay import core as _replay_core
        from gatekeeper_tpu.replay import shadow as _shadow

        try:
            _cand_docs = _reader.read_sources(args.shadow_candidate)
            _cand_rt = _replay_core.load_candidate(
                _cand_docs, compile_cache_dir=args.compile_cache,
                metrics=metrics)
            _shadow_rec = None
            if args.shadow_sink:
                _shadow_rec = _flightrec.FlightRecorder(
                    capacity=1024, sink_path=args.shadow_sink)
            shadow_lane = _shadow.ShadowLane(
                _cand_rt, serving_client=client,
                candidate_docs=_cand_docs, recorder=_shadow_rec,
                metrics=metrics).start()
            _shadow.install(shadow_lane)
            if slo_engine is not None:
                # divergence-rate breach -> automatic canary abort (the
                # objective only rides the engine when the shadow lane
                # is configured, so the hook always has its metric)
                shadow_lane.bind_slo(slo_engine)
            print(f"shadow canary active: {len(_cand_docs)} candidate "
                  f"docs (/debug/shadow"
                  + (", slo auto-abort armed" if slo_engine is not None
                     else "") + ")", file=sys.stderr)
        except Exception as e:
            print(f"shadow canary disabled: {e}", file=sys.stderr)
    kube_cluster = None
    if args.kubeconfig:
        from gatekeeper_tpu.sync.kube import KubeCluster, KubeConfig

        cfg = (KubeConfig.in_cluster() if args.kubeconfig == "in-cluster"
               else KubeConfig.from_kubeconfig(args.kubeconfig))
        kube_cluster = cluster = KubeCluster(cfg, metrics=metrics)
        print(f"informer plane: apiserver {cfg.server}", file=sys.stderr)
    else:
        cluster = FakeCluster()
    if args.management_manifests:
        # remote-cluster mode: gatekeeper-internal state (status group +
        # Secrets) lives on the management side; everything else — incl. a
        # live --kubeconfig apiserver — is the target
        from gatekeeper_tpu.sync.routing import RoutingCluster

        mgmt = FakeCluster()
        FileSource(args.management_manifests).populate(mgmt)
        cluster = RoutingCluster(mgmt, cluster)
        if kube_cluster is not None:
            kube_cluster = cluster  # audit discovery routes via the target
    export = ExportSystem()
    if args.export_dir:
        export.upsert_connection("disk", "disk", {"path": args.export_dir})
    # batched external-data join lane (extdata/lane.py): one process-wide
    # lane over the manager's provider cache — the webhook's device grid,
    # the audit sweep and mutation-placeholder resolution all dedupe
    # their keys through it; 'perkey' keeps the PR 2 per-key reference
    # behavior (external-data templates stay on the interpreter)
    from gatekeeper_tpu.externaldata.providers import ProviderCache
    from gatekeeper_tpu.extdata import lane as _extlane

    provider_cache = ProviderCache(metrics=metrics)
    extdata_lane = _extlane.ExtDataLane(
        provider_cache, mode=args.extdata_lane,
        max_keys_per_call=args.extdata_max_keys, metrics=metrics,
        fanout=args.extdata_fanout)
    _extlane.install(extdata_lane)
    if args.extdata_lane != "batched":
        print(f"extdata lane: {args.extdata_lane}", file=sys.stderr)
    mgr = Manager(client, cluster, operations=operations,
                  provider_cache=provider_cache,
                  extdata_lane=extdata_lane,
                  export_system=export, metrics=metrics,
                  readiness_retries=args.readiness_retries).start()

    if args.manifests:
        FileSource(*args.manifests).populate(cluster)
    mgr.tracker.all_populated()

    lowered = tpu.lowered_kinds()
    print(f"templates: {len(client.templates())} "
          f"({len(lowered)} on the TPU verdict path), "
          f"constraints: {len(client.constraints())}", file=sys.stderr)

    audit_mgr = None
    snapshot = None
    snap_ingester = None
    snap_spiller = None
    snap_residency = None
    spill_load = None
    warm_cache = None
    evaluator = None
    if mgr.is_assigned("audit") or args.once:
        if args.evaluate_sidecar:
            from gatekeeper_tpu.drivers.remote import RemoteEvaluator

            evaluator = RemoteEvaluator(
                tpu, violations_limit=args.constraint_violations_limit)
        else:
            # only the local path touches jax (the sidecar-mode control
            # plane stays accelerator-free)
            from gatekeeper_tpu.parallel.sharded import (
                ShardedEvaluator,
                make_mesh,
            )

            evaluator = ShardedEvaluator(
                tpu, make_mesh(),
                violations_limit=args.constraint_violations_limit,
                flatten_lane=args.flatten_lane,
                metrics=metrics,
                collect=args.collect,
                flatten_workers=args.flatten_workers)

        if kube_cluster is not None:
            # discovery-driven audit listing (auditResources,
            # pkg/audit/manager.go:369-422): every listable GVK, paged;
            # transient apiserver errors skip the sweep, never kill the pod
            def lister():
                try:
                    gvks = kube_cluster.server_preferred_gvks()
                except Exception as e:
                    print(f"audit discovery failed: {e}", file=sys.stderr)
                    return
                for gvk in gvks:
                    try:
                        yield from kube_cluster.list_iter(gvk)
                    except Exception as e:
                        print(f"audit list {gvk}: {e}", file=sys.stderr)
        else:
            def lister():
                return iter(cluster.list())
        audit_event_sink = None
        if args.emit_audit_events:
            from gatekeeper_tpu.sync import events as _events

            audit_event_sink = _events.audit_event_sink(
                _events.EventRecorder(
                    cluster, "gatekeeper-audit",
                    gk_namespace=args.gatekeeper_namespace,
                    involved_namespace=(
                        args.audit_events_involved_namespace),
                    on_error=lambda e: print(
                        f"audit event emit failed: {e}", file=sys.stderr)))
        audit_source = args.audit_source
        if audit_source == "snapshot":
            if args.evaluate_sidecar:
                # the snapshot lane slices resident columns into device
                # chunks locally (sweep_flatten_from_batch) — the
                # sidecar's RPC evaluator has no such seam
                print("--audit-source snapshot needs a local evaluator; "
                      "falling back to relist", file=sys.stderr)
                audit_source = "relist"
            else:
                from gatekeeper_tpu.snapshot import (ClusterSnapshot,
                                                     SnapshotConfig,
                                                     SnapshotSpill,
                                                     SnapshotSpiller,
                                                     WatchIngester,
                                                     gvks_of,
                                                     templates_digest)

                snapshot = ClusterSnapshot(evaluator, SnapshotConfig(),
                                           metrics=metrics)
                spill_load = None
                if args.snapshot_spill:
                    snap_spill = SnapshotSpill(
                        args.snapshot_spill, metrics=metrics,
                        compress=args.snapshot_spill_compress,
                        delta=args.snapshot_spill_delta,
                        full_every=args.snapshot_spill_full_every)
                    from gatekeeper_tpu.apis.constraints import AUDIT_EP \
                        as _AEP

                    audit_cons = [c for c in client.constraints()
                                  if c.actions_for(_AEP)]
                    spill_load = snap_spill.load(
                        snapshot, audit_cons,
                        extdata_lane=extdata_lane,
                        templates=templates_digest(client))
                    if spill_load is not None:
                        print(f"snapshot spill loaded: "
                              f"{spill_load['rows']} rows warm, zero "
                              f"relist (resubscribing from recorded rv)",
                              file=sys.stderr)
                    else:
                        print("snapshot spill miss "
                              f"({snap_spill.stats()['miss_reasons']}); "
                              "booting with a clean relist",
                              file=sys.stderr)
                watch_src = kube_cluster if kube_cluster is not None \
                    else cluster
                if kube_cluster is not None:
                    try:
                        watch_gvks = kube_cluster.server_preferred_gvks()
                    except Exception as e:
                        print(f"snapshot discovery failed: {e}",
                              file=sys.stderr)
                        watch_gvks = []
                else:
                    watch_gvks = gvks_of(cluster.list())
                snap_ingester = WatchIngester(
                    snapshot, watch_src, watch_gvks,
                    from_rvs=(spill_load or {}).get("rvs"),
                    on_error=lambda e: print(
                        f"snapshot watch subscribe failed: {e}",
                        file=sys.stderr)).start()
                if args.snapshot_spill:
                    snap_spiller = SnapshotSpiller(
                        snap_spill, snapshot,
                        rvs_fn=lambda: dict(snap_ingester.rvs),
                        extdata_lane=extdata_lane,
                        templates_fn=lambda: templates_digest(client))
                if args.snapshot_residency != "off":
                    from gatekeeper_tpu.snapshot import DeviceResidency

                    snap_residency = DeviceResidency(
                        evaluator, metrics=metrics,
                        mode=args.snapshot_residency)
                    _gc = getattr(tpu, "gen_coord", None)
                    if _gc is not None:
                        # generation swaps drop the device mirrors
                        # eagerly (new schemas/layouts)
                        _gc.attach_residency(snap_residency)
                print(f"resident snapshot active: watching "
                      f"{len(watch_gvks)} GVKs, resync every "
                      f"{args.snapshot_resync_every} intervals",
                      file=sys.stderr)
        audit_mgr = AuditManager(
            client,
            lister=lister,
            config=AuditConfig(
                interval_s=args.audit_interval,
                violations_limit=args.constraint_violations_limit,
                chunk_size=args.audit_chunk_size,
                pipeline=args.pipeline,
                pipeline_flatten_workers=args.pipeline_flatten_workers,
                shard_chunks=args.shard_chunks,
                audit_source=audit_source,
                resync_every=args.snapshot_resync_every,
                resync_rotate=args.snapshot_resync_rotate,
                expand_generated=args.audit_expand,
            ),
            evaluator=evaluator,
            export_system=export,  # Connection CRs register here too
            event_sink=audit_event_sink,
            log_violations=args.log_denies,
            metrics=metrics,
            snapshot=snapshot,
            expansion_system=mgr.expansion_system,
            spiller=snap_spiller,
            residency=snap_residency,
        )
        if snapshot is not None and snapshot.warm_loaded \
                and spill_load is not None:
            audit_mgr.restore_spill_aux(spill_load.get("aux") or {})
        if args.compile_cache and not args.evaluate_sidecar \
                and not args.once:
            # warm-state replay (drivers/generation.WarmStateCache):
            # re-land the fused sweep traces + the admission warm-ref
            # kernels recorded by the previous process, so the first
            # tick/burst after this restart retraces nothing — the
            # persistent XLA cache under the same dir answers the
            # compiles
            from gatekeeper_tpu.drivers.generation import WarmStateCache

            warm_cache = WarmStateCache(args.compile_cache,
                                        metrics=metrics)
            rep = warm_cache.replay(tpu, evaluator)
            if rep["hit"]:
                print(f"warm state replayed: {rep['sweep_traces']} "
                      f"sweep traces landed", file=sys.stderr)

    def export_trace():
        if tracer is None or not args.trace:
            return
        from gatekeeper_tpu.observability import write_chrome_trace

        n = write_chrome_trace(args.trace, tracer)
        print(f"trace: {n} events ({tracer.kept} traces kept, "
              f"{tracer.sampled_out} sampled out) -> {args.trace} "
              f"(load in ui.perfetto.dev or chrome://tracing)",
              file=sys.stderr)

    if args.once:
        run = audit_mgr.audit()
        if snap_spiller is not None:
            # a --once sweep is a natural spill point: the NEXT --once
            # (or server boot) warm-starts off it, mirroring how the
            # compile cache serves one-shot runs
            snap_spiller.spill_now()
        total = sum(run.total_violations.values())
        print(f"audit: {run.total_objects} objects, {total} violations "
              f"in {run.duration_s:.2f}s "
              f"(flatten_workers={run.flatten_workers}, "
              f"n_devices={run.n_devices}, "
              f"shard_chunks={run.shard_chunks})"
              + (f" [INCOMPLETE: {run.failed_chunks} chunks dropped, "
                 f"{run.retried_chunks} retried]" if run.incomplete
                 else ""), file=sys.stderr)
        for key, kept in sorted(run.kept.items()):
            for v in kept:
                print(f"  {key[0]}/{key[1]}: {v.kind} "
                      f"{v.namespace + '/' if v.namespace else ''}{v.name}: "
                      f"{v.message}")
        export_trace()
        return 0

    # namespace lookup for the webhook hot path: with a live apiserver,
    # serve from a watch-fed cache (the reference's cached client with
    # API-reader fallback, policy.go:694-702) — never a blocking GET per
    # admission request
    if kube_cluster is not None:
        ns_cache: dict = {}

        def _ns_event(ev):
            name = (ev.obj.get("metadata") or {}).get("name", "")
            if ev.type == "DELETED":
                ns_cache.pop(name, None)
            else:
                ns_cache[name] = ev.obj

        kube_cluster.subscribe(("", "v1", "Namespace"), _ns_event,
                               replay=True)

        def namespace_lookup(name):
            hit = ns_cache.get(name)
            if hit is not None:
                return hit
            return kube_cluster.get(("", "v1", "Namespace"), "", name)
    else:
        def namespace_lookup(name):
            return cluster.get(("", "v1", "Namespace"), "", name)

    batcher = Batcher(client, stats=args.log_stats_admission,
                      small_batch=args.webhook_small_batch,
                      metrics=metrics).start()
    mutation_batcher = None
    mutation_handler = None
    if mgr.is_assigned("mutation-webhook"):
        if args.mutate_lane == "host":
            mutation_handler = MutationHandler(
                mgr.mutation_system,
                namespace_lookup=namespace_lookup,
                process_excluder=mgr.excluder,
            )
        else:
            # the batched lane: mutate reviews coalesce into one
            # columnar pass, sharing the validation path's overload gate
            # and zero-loss drain (README 'Batched mutation & expansion')
            from gatekeeper_tpu.mutlane import (BatchedMutationHandler,
                                                MutationBatcher,
                                                MutationLane)

            mut_lane = MutationLane(
                mgr.mutation_system, metrics=metrics,
                differential=args.mutate_lane == "differential",
                ingest=args.mutate_ingest,
                # mutator churn recompiles on the generation thread too
                # (bursts keep the previous revision until the install)
                coordinator=getattr(tpu, "gen_coord", None))
            mutation_batcher = MutationBatcher(
                mut_lane, metrics=metrics).start()
            mutation_handler = BatchedMutationHandler(
                mgr.mutation_system,
                lane=mut_lane,
                namespace_lookup=namespace_lookup,
                process_excluder=mgr.excluder,
                batcher=mutation_batcher,
                metrics=metrics,
                overload=overload_ctl,
                failure_policy=("ignore" if args.fail_open_on_error
                                else args.webhook_failure_policy),
            )
    admission_sink = None
    if args.emit_admission_events:
        from gatekeeper_tpu.sync import events as _events

        admission_sink = _events.admission_event_sink(
            _events.EventRecorder(
                cluster, "gatekeeper-webhook",
                gk_namespace=args.gatekeeper_namespace,
                involved_namespace=args.admission_events_involved_namespace,
                on_error=lambda e: print(
                    f"admission event emit failed: {e}", file=sys.stderr)))
    server = None
    if mgr.is_assigned("webhook") or mgr.is_assigned("mutation-webhook"):
        # warm every grid-lane pad bucket before serving: readiness
        # already gates traffic (the reference's warm-cache contract,
        # readiness/setup.go:28-41) and a lazily-compiled batch shape
        # would otherwise stall the first saturated admission burst for
        # seconds
        if client.templates():
            from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
            from gatekeeper_tpu.target.review import AugmentedUnstructured

            _pod = {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "warmup", "namespace": "default"},
                    "spec": {"containers": [
                        {"name": "c", "image": "warmup"}]}}
            _warm = [AugmentedUnstructured(object=dict(_pod),
                                           source=SOURCE_ORIGINAL)
                     for _ in range(batcher.max_batch)]
            n = max(1, batcher.small_batch + 1)
            while n <= batcher.max_batch:
                client.review_batch(_warm[:n])
                n *= 2
            client.review_batch(_warm)
        certfile = keyfile = None
        if args.certs_dir:
            import os

            if kube_cluster is not None:
                # live cluster: the cert-controller-equivalent bootstrap —
                # chain lives in the cert Secret (one replica generates,
                # all consume), caBundle injected into the webhook configs
                from gatekeeper_tpu.webhook.certs import \
                    ensure_cluster_certs

                certfile, keyfile = ensure_cluster_certs(
                    kube_cluster, args.certs_dir)
                args.certs_dir = os.path.dirname(certfile)
            else:
                from gatekeeper_tpu.webhook.certs import generate_certs

                if not os.path.exists(
                        os.path.join(args.certs_dir, "tls.crt")):
                    generate_certs(args.certs_dir)
                certfile = os.path.join(args.certs_dir, "tls.crt")
                keyfile = os.path.join(args.certs_dir, "tls.key")
        server = WebhookServer(
            client_ca_file=args.client_ca_file or None,
            tls_min_version=args.tls_min_version,
            enable_profile=args.enable_profile,
            validation_handler=ValidationHandler(
                client,
                expansion_system=mgr.expansion_system,
                process_excluder=mgr.excluder,
                namespace_lookup=namespace_lookup,
                batcher=batcher,
                log_denies=args.log_denies,
                event_sink=admission_sink,
                metrics=metrics,
                fail_open=args.fail_open_on_error,
                failure_policy=("ignore" if args.fail_open_on_error
                                else args.webhook_failure_policy),
                deadline_budget_s=args.webhook_deadline,
                trace_config=lambda: mgr.validation_traces,
                log_stats=args.log_stats_admission,
                overload=overload_ctl,
                snapshot=snapshot,  # warm namespace/referential cache
            ) if mgr.is_assigned("webhook") else None,
            mutation_handler=mutation_handler,
            namespace_label_handler=NamespaceLabelHandler(
                exempt_namespaces=args.exempt_namespace,
                exempt_prefixes=args.exempt_namespace_prefix,
                exempt_suffixes=args.exempt_namespace_suffix,
            ),
            port=args.port,
            certfile=certfile,
            keyfile=keyfile,
            # drain pulls readiness BEFORE the listener closes (the LB
            # deregisters during --shutdown-delay)
            readiness_check=lambda: (not drain.draining
                                     and mgr.tracker.satisfied()),
            readiness_stats=mgr.tracker.stats,
            metrics=metrics,
            reuse_port=args.reuse_port,
            backlog=args.webhook_backlog,
            batcher=batcher,
            mutation_batcher=mutation_batcher,
            cost_attribution=cost_attr,
            slo_engine=slo_engine,
            flight_recorder=flight_rec,
        ).start()
        print(f"webhook serving on :{server.port}", file=sys.stderr)
        if args.certs_dir and args.cert_rotation_check_s > 0:
            # check-s <= 0 disables rotation (SO_REUSEPORT worker
            # children: only the parent rotates, or N processes would
            # race renewal-time generation into mismatched pairs)
            import threading

            from gatekeeper_tpu.webhook.certs import rotation_loop

            rot_stop = threading.Event()
            threading.Thread(
                target=rotation_loop,
                args=(args.certs_dir, server, rot_stop,
                      args.cert_rotation_check_s),
                kwargs={"cluster": kube_cluster},
                daemon=True,
            ).start()

    # boot reconcile + warm are done: flip template churn to the
    # background generation lane (README "Generations & compile cache")
    # — from here on a ConstraintTemplate add/edit stages + enqueues,
    # the compile thread builds/warms the next generation, and the swap
    # lands off the serving path
    if mgr.begin_background_compile():
        print("generation swap active: post-boot template churn "
              "compiles in the background", file=sys.stderr)

    # graceful shutdown (the drain state machine, README "Overload &
    # drain semantics"): on SIGTERM readiness flips 503 {draining:true}
    # immediately (the LB deregisters during --shutdown-delay while the
    # listener KEEPS serving), then the listener stops accepting and
    # in-flight handlers + the batcher queue drain to completion within
    # --drain-timeout, the tracer/metrics flush, and worker children
    # drain in sequence — zero accepted verdicts lost
    import signal
    import threading

    stopping = threading.Event()

    def _on_term(signum, frame):
        if not drain.begin(f"signal {signum}"):
            return  # a second SIGTERM while already draining
        print(f"signal {signum}: draining"
              + (f" (serving {args.shutdown_delay:.0f}s more for LB "
                 f"deregistration)" if args.shutdown_delay else ""),
              file=sys.stderr)
        if server is not None:
            server.begin_drain()  # healthz 503 + retire keep-alives
        for wp in worker_procs:  # children start their own drains now
            wp.terminate()
        if args.shutdown_delay:
            time.sleep(args.shutdown_delay)
        stopping.set()
        if audit_mgr is not None:
            audit_mgr.stop()

    signal.signal(signal.SIGTERM, _on_term)

    try:
        if audit_mgr is not None:
            audit_mgr.run_forever()
        else:
            while not stopping.wait(1.0):
                pass
    except KeyboardInterrupt:
        pass
    finally:
        drain.begin("shutdown")
        if server:
            # stops accepting, then drains in-flight handlers AND the
            # batcher queue inside the budget before closing
            drained = server.stop(drain_timeout=args.drain_timeout)
            if not drained:
                print(f"WARNING: drain exceeded --drain-timeout "
                      f"{args.drain_timeout:.0f}s; in-flight work "
                      f"abandoned", file=sys.stderr)
        batcher.stop()  # idempotent (server.stop drained it already)
        if mutation_batcher is not None:
            mutation_batcher.stop()
        if snap_spiller is not None:
            # final spill (idempotent with run_forever's exit flush): a
            # clean drain never loses the resident state it paid for
            snap_spiller.stop(flush=True)
        if snap_ingester is not None:
            snap_ingester.stop()
        if warm_cache is not None:
            # persist the warm execution state beside the compile cache
            # so the NEXT process replays traces instead of retracing
            warm_cache.save(tpu, evaluator)
        _gc = getattr(tpu, "gen_coord", None)
        if _gc is not None:
            _gc.stop()
        if shadow_lane is not None:
            from gatekeeper_tpu.replay import shadow as _shadow

            _shadow.uninstall()
            shadow_lane.stop()
            if shadow_lane.recorder is not None:
                shadow_lane.recorder.close()
        if slo_engine is not None:
            slo_engine.stop()
        if flight_rec is not None:
            flight_rec.close()  # flush the JSONL black box
        export_trace()  # tracer flush happens after the last span closed
        # worker children drain in sequence: each runs this same
        # machinery; the parent waits for them one at a time so every
        # replica finishes its in-flight verdicts before the port dies
        for wp in worker_procs:
            wp.terminate()
        for wp in worker_procs:
            try:
                wp.wait(timeout=max(5.0, args.drain_timeout))
            except Exception:
                wp.kill()
        dt = drain.finish()
        if drain.drain_seconds is not None and server is not None:
            print(f"drain complete in {dt:.2f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
