"""Location-path DSL parser.

Reference grammar (pkg/mutation/path/token + path/parser):
    spec.containers[name: foo].securityContext
    spec.containers[name: *].image
    metadata.labels."dotted.key"
Object nodes are field names (quotable with single/double quotes, escapes
allowed); list nodes are ``[keyField: keyValue]`` where keyValue ``*`` globs
every item.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Union


class PathParseError(ValueError):
    pass


@dataclass(frozen=True)
class ObjectNode:
    name: str


@dataclass(frozen=True)
class ListNode:
    key_field: str
    key_value: Union[str, int, None]  # None = glob (*)

    @property
    def glob(self) -> bool:
        return self.key_value is None


def parse(path: str):
    """Parse a location string into a list of nodes."""
    nodes = []
    i, n = 0, len(path)

    def read_ident(i):
        if i < n and path[i] in "\"'":
            quote = path[i]
            i += 1
            buf = []
            while i < n and path[i] != quote:
                if path[i] == "\\" and i + 1 < n:
                    buf.append(path[i + 1])
                    i += 2
                else:
                    buf.append(path[i])
                    i += 1
            if i >= n:
                raise PathParseError(f"unterminated quote in {path!r}")
            return "".join(buf), i + 1
        buf = []
        while i < n and path[i] not in ".[]:":
            if path[i] == "\\" and i + 1 < n:
                buf.append(path[i + 1])
                i += 2
            else:
                buf.append(path[i])
                i += 1
        if not buf:
            raise PathParseError(f"empty path segment in {path!r} at {i}")
        return "".join(buf), i

    while i < n:
        name, i = read_ident(i)
        nodes.append(ObjectNode(name.strip()))
        # optional list spec(s)
        while i < n and path[i] == "[":
            j = path.find("]", i)
            if j < 0:
                raise PathParseError(f"unterminated [ in {path!r}")
            inner = path[i + 1 : j]
            if ":" not in inner:
                raise PathParseError(
                    f"list spec must be [key: value] in {path!r}"
                )
            key, _, val = inner.partition(":")
            key = key.strip().strip("\"'")
            val = val.strip()
            if val == "*":
                nodes.append(ListNode(key_field=key, key_value=None))
            else:
                val = val.strip("\"'")
                nodes.append(ListNode(key_field=key, key_value=val))
            i = j + 1
        if i < n:
            if path[i] != ".":
                raise PathParseError(
                    f"expected '.' at offset {i} in {path!r}"
                )
            i += 1
            if i >= n:
                raise PathParseError(f"trailing '.' in {path!r}")
    if not nodes:
        raise PathParseError("empty path")
    return nodes


def to_string(nodes) -> str:
    out = []
    for node in nodes:
        if isinstance(node, ObjectNode):
            if out:
                out.append(".")
            name = node.name
            if any(c in name for c in ".[]:\"'"):
                name = '"%s"' % name.replace('"', '\\"')
            out.append(name)
        else:
            v = "*" if node.glob else node.key_value
            out.append(f"[{node.key_field}: {v}]")
    return "".join(out)
