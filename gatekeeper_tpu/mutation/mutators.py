"""The four mutator kinds.

Reference (pkg/mutation/mutators):
- **Assign** (assign/assign_mutator.go): arbitrary value at location (outside
  metadata), ``assignIf`` in/notIn gating, pathTests, value sources
  value / fromMetadata / externalData.
- **AssignMetadata** (assignmeta/assignmeta_mutator.go): only
  metadata.labels.* / metadata.annotations.*, string value, never overwrites.
- **ModifySet** (modifyset/modify_set_mutator.go): treat a list as a set;
  merge (append missing) or prune (remove present).
- **AssignImage** (assignimage/assignimage_mutator.go + imageparser.go):
  split an image ref into [domain/]path[:tag|@digest] and override components.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.match.match import Matchable, matches
from gatekeeper_tpu.mutation import path_parser
from gatekeeper_tpu.mutation.core import (
    MutateError,
    PathTester,
    Setter,
    _deep_equal,
    mutate,
)
from gatekeeper_tpu.mutation.path_parser import ListNode, ObjectNode
from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of, name_of

MUTATIONS_GROUP = "mutations.gatekeeper.sh"


class MutatorError(Exception):
    pass


@dataclass(frozen=True)
class MutatorID:
    kind: str
    name: str

    def __str__(self):
        return f"{self.kind}/{self.name}"


class BaseMutator:
    kind = ""

    def __init__(self, obj: dict):
        group, _, kind = gvk_of(obj)
        if group != MUTATIONS_GROUP:
            raise MutatorError(f"mutator group must be {MUTATIONS_GROUP}")
        if kind != self.kind:
            raise MutatorError(f"expected kind {self.kind}, got {kind}")
        name = name_of(obj)
        if not name:
            raise MutatorError("mutator has no metadata.name")
        self.id = MutatorID(kind=kind, name=name)
        self.spec = obj.get("spec") or {}
        self.match_spec = self.spec.get("match") or {}
        self.apply_to = self.spec.get("applyTo") or []
        self.raw = obj
        location = self.spec.get("location", "")
        if not location:
            raise MutatorError(f"{self.id}: missing spec.location")
        self.location = location
        self.path = path_parser.parse(location)
        self.tester = self._build_tester()

    def _build_tester(self) -> PathTester:
        tests = []
        for t in (self.spec.get("parameters") or {}).get("pathTests") or []:
            sub = t.get("subPath", "")
            cond = t.get("condition", "")
            sub_nodes = path_parser.parse(sub)
            if sub_nodes != self.path[: len(sub_nodes)]:
                raise MutatorError(
                    f"{self.id}: pathTest subPath {sub!r} is not a prefix of "
                    f"location"
                )
            tests.append((len(sub_nodes) - 1, cond))
        return PathTester(tests)

    # --- applicability ---------------------------------------------------
    def applies_to(self, obj: dict) -> bool:
        """ApplyTo GVK allowlist — required on mutators
        (reference: match/apply_to.go)."""
        group, version, kind = gvk_of(obj)
        for entry in self.apply_to:
            if (
                group in (entry.get("groups") or [])
                and version in (entry.get("versions") or [])
                and kind in (entry.get("kinds") or [])
            ):
                return True
        return False

    def matches(self, obj: dict, namespace: Optional[dict] = None,
                source: str = "") -> bool:
        if not self.applies_to(obj):
            return False
        return matches(self.match_spec, Matchable(obj=obj, namespace=namespace,
                                                  source=source))

    def mutate_obj(self, obj: dict) -> bool:
        raise NotImplementedError

    def path_schema(self):
        """(depth-keyed node kinds) for conflict detection."""
        return [
            ("list", node.key_field) if isinstance(node, ListNode)
            else ("object", node.name)
            for node in self.path
        ]


# --- Assign ----------------------------------------------------------------


class _AssignSetter(Setter):
    def __init__(self, value: Any, assign_if: dict,
                 placeholder_factory=None):
        self.value = value
        self.assign_if = assign_if or {}
        self.placeholder_factory = placeholder_factory

    def _gate(self, current: Any, exists: bool) -> bool:
        in_list = self.assign_if.get("in")
        not_in = self.assign_if.get("notIn")
        if in_list is not None:
            if not exists or not any(_deep_equal(current, v) for v in in_list):
                return False
        if not_in is not None:
            if exists and any(_deep_equal(current, v) for v in not_in):
                return False
        return True

    def set_value(self, parent, key, current, exists):
        if not self._gate(current, exists):
            return None, False
        if self.placeholder_factory is not None:
            from gatekeeper_tpu.externaldata.placeholders import (
                ExternalDataPlaceholder,
            )

            if isinstance(current, ExternalDataPlaceholder):
                # already placed this iteration round: fixed point
                return None, False
            # external data: the placeholder carries the CURRENT value — for
            # dataSource ValueAtLocation it becomes the provider key
            # (system_external_data.go)
            return self.placeholder_factory(current), True
        return copy.deepcopy(self.value), True


class AssignMutator(BaseMutator):
    kind = "Assign"

    def __init__(self, obj: dict):
        super().__init__(obj)
        if isinstance(self.path[0], ObjectNode) and (
            self.path[0].name == "metadata"
        ):
            # reference: Assign cannot mutate metadata (assign_mutator.go
            # validation) — AssignMetadata owns that subtree
            raise MutatorError(
                f"{self.id}: cannot mutate metadata with Assign"
            )
        params = self.spec.get("parameters") or {}
        assign = params.get("assign") or {}
        if "value" in assign:
            self.value = assign["value"]
            self.from_metadata = None
            self.external = None
        elif "fromMetadata" in assign:
            self.value = None
            self.from_metadata = assign["fromMetadata"].get("field", "")
            self.external = None
        elif "externalData" in assign:
            self.value = None
            self.from_metadata = None
            self.external = assign["externalData"]
        else:
            raise MutatorError(f"{self.id}: assign needs value/fromMetadata/"
                               "externalData")
        self.assign_if = params.get("assignIf") or {}

    def mutate_obj(self, obj: dict) -> bool:
        value = self.value
        if self.from_metadata is not None:
            meta = obj.get("metadata") or {}
            if self.from_metadata == "namespace":
                value = meta.get("namespace", "")
            elif self.from_metadata == "name":
                value = meta.get("name", "")
            else:
                raise MutateError(
                    f"unknown fromMetadata field {self.from_metadata!r}"
                )
        placeholder_factory = None
        if self.external is not None:
            from gatekeeper_tpu.externaldata.placeholders import (
                ExternalDataPlaceholder,
            )

            ext = self.external

            def placeholder_factory(current):
                return ExternalDataPlaceholder(
                    provider=ext.get("provider", ""),
                    data_source=ext.get("dataSource", "ValueAtLocation"),
                    default=ext.get("default"),
                    failure_policy=ext.get("failurePolicy", "Fail"),
                    location=self.location,
                    original_value=current,
                )

        setter = _AssignSetter(value, self.assign_if, placeholder_factory)
        return mutate(obj, self.path, setter, self.tester)


# --- AssignMetadata --------------------------------------------------------


class _AssignMetaSetter(Setter):
    def __init__(self, value: str):
        self.value = value

    def set_value(self, parent, key, current, exists):
        if exists:
            return None, False  # never overwrite (assignmeta_mutator.go)
        return self.value, True


class AssignMetadataMutator(BaseMutator):
    kind = "AssignMetadata"

    def applies_to(self, obj: dict) -> bool:
        # AssignMetadata has no applyTo field — it applies to every GVK
        # (reference: assignmeta has no ApplyTo; see the basic-expansion
        # fixture where demo-annotation-owner carries only match)
        return True

    def __init__(self, obj: dict):
        super().__init__(obj)
        ok = (
            len(self.path) == 3
            and all(isinstance(p, ObjectNode) for p in self.path)
            and self.path[0].name == "metadata"
            and self.path[1].name in ("labels", "annotations")
        )
        if not ok:
            raise MutatorError(
                f"{self.id}: AssignMetadata location must be "
                "metadata.labels.<k> or metadata.annotations.<k>"
            )
        assign = (self.spec.get("parameters") or {}).get("assign") or {}
        value = assign.get("value")
        if not isinstance(value, str):
            raise MutatorError(
                f"{self.id}: AssignMetadata value must be a string"
            )
        self.value = value

    def mutate_obj(self, obj: dict) -> bool:
        return mutate(obj, self.path, _AssignMetaSetter(self.value),
                      self.tester)


# --- ModifySet -------------------------------------------------------------


class _ModifySetSetter(Setter):
    def __init__(self, values: list, operation: str):
        self.values = values
        self.operation = operation

    def set_value(self, parent, key, current, exists):
        if self.operation == "merge":
            base = list(current) if isinstance(current, list) else []
            out = list(base)
            for v in self.values:
                if not any(_deep_equal(v, e) for e in out):
                    out.append(copy.deepcopy(v))
            return out, True
        if self.operation == "prune":
            if not exists or not isinstance(current, list):
                return None, False
            out = [e for e in current
                   if not any(_deep_equal(v, e) for v in self.values)]
            return out, True
        raise MutateError(f"unknown ModifySet operation {self.operation!r}")


class ModifySetMutator(BaseMutator):
    kind = "ModifySet"

    def __init__(self, obj: dict):
        super().__init__(obj)
        params = self.spec.get("parameters") or {}
        values = (params.get("values") or {}).get("fromList")
        if not isinstance(values, list):
            raise MutatorError(f"{self.id}: parameters.values.fromList "
                               "required")
        self.values = values
        self.operation = params.get("operation", "merge") or "merge"
        if self.operation not in ("merge", "prune"):
            raise MutatorError(
                f"{self.id}: operation must be merge or prune"
            )

    def mutate_obj(self, obj: dict) -> bool:
        return mutate(obj, self.path,
                      _ModifySetSetter(self.values, self.operation),
                      self.tester)


# --- AssignImage -----------------------------------------------------------


def split_image(image: str) -> tuple[str, str, str]:
    """(domain, path, tag) of an image ref
    (reference: assignimage/imageparser.go — domain is the first component
    when it contains '.' or ':' or equals 'localhost'; tag keeps its ':' /
    '@' prefix)."""
    rest = image
    domain = ""
    slash = rest.find("/")
    if slash >= 0:
        first = rest[:slash]
        if "." in first or ":" in first or first == "localhost":
            domain = first
            rest = rest[slash + 1:]
    tag = ""
    at = rest.find("@")
    if at >= 0:
        tag = rest[at:]
        rest = rest[:at]
    else:
        colon = rest.rfind(":")
        if colon >= 0:
            tag = rest[colon:]
            rest = rest[:colon]
    return domain, rest, tag


class _AssignImageSetter(Setter):
    def __init__(self, domain: str, path: str, tag: str):
        self.domain = domain
        self.path = path
        self.tag = tag

    def set_value(self, parent, key, current, exists):
        cur = current if isinstance(current, str) else ""
        domain, pth, tag = split_image(cur)
        domain = self.domain or domain
        pth = self.path or pth
        tag = self.tag or tag
        out = (f"{domain}/" if domain else "") + pth + tag
        return out, True


class AssignImageMutator(BaseMutator):
    kind = "AssignImage"

    def __init__(self, obj: dict):
        super().__init__(obj)
        params = self.spec.get("parameters") or {}
        self.assign_domain = params.get("assignDomain", "") or ""
        self.assign_path = params.get("assignPath", "") or ""
        self.assign_tag = params.get("assignTag", "") or ""
        if not (self.assign_domain or self.assign_path or self.assign_tag):
            raise MutatorError(
                f"{self.id}: at least one of assignDomain/assignPath/"
                "assignTag required"
            )
        if self.assign_tag and self.assign_tag[0] not in ":@":
            raise MutatorError(
                f"{self.id}: assignTag must start with ':' or '@'"
            )

    def mutate_obj(self, obj: dict) -> bool:
        setter = _AssignImageSetter(self.assign_domain, self.assign_path,
                                    self.assign_tag)
        return mutate(obj, self.path, setter, self.tester)


MUTATOR_KINDS = {
    "Assign": AssignMutator,
    "AssignMetadata": AssignMetadataMutator,
    "ModifySet": ModifySetMutator,
    "AssignImage": AssignImageMutator,
}


def from_unstructured(obj: dict) -> BaseMutator:
    _, _, kind = gvk_of(obj)
    cls = MUTATOR_KINDS.get(kind)
    if cls is None:
        raise MutatorError(f"unknown mutator kind {kind!r}")
    return cls(obj)
