"""Mutation system: ordered registry + fixed-point apply loop.

Reference: pkg/mutation/system.go —
- mutators sorted by ID, applied in order (system.go:146-246)
- iterate until no mutator changes the object; max ``len(mutators)+1``
  iterations, else ErrNotConverging (system.go:174-246)
- mutators whose path schemas conflict (same node treated as object by one
  and list by another) are ALL disabled (pkg/mutation/schema, ErrConflicting-
  Schema)
- external-data placeholders resolve at convergence
  (system_external_data.go)
"""

from __future__ import annotations

import copy
from typing import Any, Optional, Sequence

from gatekeeper_tpu.mutation.core import _deep_equal
from gatekeeper_tpu.mutation.mutators import BaseMutator, MutatorID, from_unstructured
from gatekeeper_tpu.mutation.path_parser import ListNode, ObjectNode


class NotConvergingError(Exception):
    """Reference: ErrNotConverging (system.go:34)."""


class MutationSystem:
    def __init__(self, reporter=None, provider_cache=None):
        self._mutators: dict[MutatorID, BaseMutator] = {}
        self._conflicts: set[MutatorID] = set()
        self.reporter = reporter
        self.provider_cache = provider_cache
        # monotone registry revision: every upsert/remove bumps it, and
        # compiled artifacts (the batched-lane program, the device
        # prefilter) key their caches on it so mutator churn invalidates
        # them.  Initialized here — the old lazy __dict__.get conjuring
        # meant a never-mutated system had NO _revision attribute at all
        # and cache keys silently defaulted
        self._revision = 0
        # iterations the last ``mutate`` ran until convergence (1 = the
        # object was already at fixed point); the batched lane observes
        # this into gatekeeper_mutation_convergence_iterations
        self.last_iterations = 0

    def revision(self) -> int:
        """Registry revision, the compiled-lane cache key."""
        return self._revision

    # --- registry (reference: Upsert system.go:80, Remove :121) ----------
    def upsert(self, mutator: BaseMutator) -> None:
        self._mutators[mutator.id] = mutator
        self._revision += 1
        self._recompute_conflicts()

    def upsert_unstructured(self, obj: dict) -> BaseMutator:
        m = from_unstructured(obj)
        self.upsert(m)
        return m

    def remove(self, mutator_id: MutatorID) -> None:
        self._mutators.pop(mutator_id, None)
        self._revision += 1
        self._recompute_conflicts()

    def get(self, mutator_id: MutatorID) -> Optional[BaseMutator]:
        return self._mutators.get(mutator_id)

    def mutators(self) -> list[BaseMutator]:
        return [self._mutators[k] for k in sorted(self._mutators,
                                                  key=str)]

    def active(self) -> list[BaseMutator]:
        """Mutators that may run: registry order minus schema conflicts
        (the set both the fixed-point loop and the batched lane apply)."""
        return [m for m in self.mutators() if m.id not in self._conflicts]

    def conflicts(self) -> set:
        return set(self._conflicts)

    def _recompute_conflicts(self) -> None:
        """Schema conflict detection (reference: pkg/mutation/schema) —
        if two mutators disagree on whether a path node is an object or a
        keyed list, none of the conflicting mutators may run."""
        by_prefix: dict[tuple, dict] = {}
        conflicts: set[MutatorID] = set()
        for m in self._mutators.values():
            prefix: tuple = ()
            for node in m.path:
                if isinstance(node, ObjectNode):
                    kind, detail = "object", node.name
                    key = ("o", node.name)
                else:
                    kind, detail = "list", node.key_field
                    key = ("l",)
                slot = by_prefix.setdefault(prefix, {})
                entry = slot.setdefault("kinds", {})
                entry.setdefault(kind, set()).add(m.id)
                if kind == "list":
                    keyfields = slot.setdefault("keyfields", {})
                    keyfields.setdefault(node.key_field, set()).add(m.id)
                prefix = prefix + (key,)
        for slot in by_prefix.values():
            kinds = slot.get("kinds", {})
            if "object" in kinds and "list" in kinds:
                for ids in kinds.values():
                    conflicts.update(ids)
            keyfields = slot.get("keyfields", {})
            if len(keyfields) > 1:
                for ids in keyfields.values():
                    conflicts.update(ids)
        self._conflicts = conflicts

    # --- the apply loop (reference: Mutate system.go:146-246) ------------
    def mutate(self, obj: dict, namespace: Optional[dict] = None,
               source: str = "") -> bool:
        """Fixed-point application; mutates ``obj`` in place, returns
        changed?"""
        active = self.active()
        self.last_iterations = 0
        if not active:
            return False
        original = copy.deepcopy(obj)
        max_iterations = len(active) + 1
        any_change = False
        for it in range(max_iterations):
            iteration_changed = False
            for m in active:
                if not m.matches(obj, namespace=namespace, source=source):
                    continue
                old = copy.deepcopy(obj)
                if m.mutate_obj(obj) and not _deep_equal(old, obj):
                    iteration_changed = True
                    any_change = True
            if not iteration_changed:
                self.last_iterations = it + 1
                self._resolve_placeholders(obj)
                return any_change
        # restore: a non-converging system must not half-mutate (the
        # reference returns the error without applying)
        obj.clear()
        obj.update(original)
        raise NotConvergingError(
            f"mutation system failed to converge after {max_iterations} "
            "iterations"
        )

    def mutate_batch(self, objects: list, namespace=None,
                     source: str = "") -> list:
        """Batch mutation with the device path-match prefilter (BASELINE
        config #4): the [M, N] would-change grid runs once on device; the
        host fixed-point walk runs ONLY on objects some mutator would
        actually touch (plus every object when non-lowerable mutators
        exist — they stay host-authoritative).  Returns changed flags."""
        active = self.active()
        if not active or not objects:
            return [False] * len(objects)
        from gatekeeper_tpu.mutation.device import MutationPrefilter

        # cache keyed on the system REVISION (not just ids: an in-place
        # upsert changing a mutator's value/location must recompile)
        rev = self._revision
        pre = self.__dict__.get("_prefilter")
        if pre is None or self.__dict__.get("_prefilter_rev") != rev:
            pre = MutationPrefilter()
            for m in active:
                pre.add_mutator(m)
            self.__dict__["_prefilter"] = pre
            self.__dict__["_prefilter_rev"] = rev
        all_lowered = len(pre.lowered_ids()) == len(active)
        changed = [False] * len(objects)
        if all_lowered:
            # the walk must also run where it would ERROR, so callers see
            # the same MutateError the per-object path raises
            needs = (pre.would_change(active, objects)
                     | pre.would_error(active, objects)).any(axis=0)
        else:
            needs = [True] * len(objects)
        for oi, obj in enumerate(objects):
            if needs[oi]:
                changed[oi] = self.mutate(obj, namespace=namespace,
                                          source=source)
        return changed

    def _resolve_placeholders(self, obj: Any) -> None:
        """Resolve external-data placeholders at convergence
        (reference: system.go:214 → system_external_data.go)."""
        from gatekeeper_tpu.externaldata.placeholders import (
            ExternalDataPlaceholder,
        )

        # pass 1: collect every placeholder, then warm the cache with ONE
        # concurrent multi-provider prefetch (async batch join) so pass 2's
        # per-placeholder resolve() hits cache instead of serial RTTs
        pending = []

        def collect(node):
            if isinstance(node, dict):
                for v in node.values():
                    if isinstance(v, ExternalDataPlaceholder):
                        pending.append(v)
                    else:
                        collect(v)
            elif isinstance(node, list):
                for v in node:
                    if isinstance(v, ExternalDataPlaceholder):
                        pending.append(v)
                    else:
                        collect(v)

        collect(obj)
        # batched external-data join (extdata/lane.py): with a device-join
        # lane active, every placeholder's key dedupes into ONE lane
        # resolution per provider (warm columns = zero transport); the
        # per-key prefetch+resolve below stays the authoritative reference
        # (and the perkey lane mode's path)
        resolved = None
        lane = self._extdata_lane()
        if lane is not None and pending:
            resolved = lane.resolve_placeholders(pending)
        elif self.provider_cache is not None and len(pending) > 1:
            self.provider_cache.prefetch(
                (ph.provider, ph.original_value) for ph in pending)

        def resolve(ph):
            if resolved is not None:
                return self._apply_failure_policy(
                    ph, resolved.get((ph.provider, ph.original_value)))
            return self._resolve_one(ph)

        def walk(node):
            if isinstance(node, dict):
                for k, v in list(node.items()):
                    if isinstance(v, ExternalDataPlaceholder):
                        node[k] = resolve(v)
                    else:
                        walk(v)
            elif isinstance(node, list):
                for i, v in enumerate(node):
                    if isinstance(v, ExternalDataPlaceholder):
                        node[i] = resolve(v)
                    else:
                        walk(v)

        walk(obj)

    def _extdata_lane(self):
        """The batched lane, when one is active in a device-join mode
        (batched/differential); None keeps the per-key reference path."""
        from gatekeeper_tpu.extdata import lane as lane_mod

        lane = lane_mod.active()
        if lane is not None and lane.device_join():
            return lane
        return None

    def _apply_failure_policy(self, ph, value_err):
        """Failure-policy semantics over a lane-resolved (value, error)
        pair — EXACTLY ProviderCache.resolve's Fail | Ignore |
        UseDefault behavior, so the batched and per-key paths produce
        identical mutations."""
        from gatekeeper_tpu.externaldata.providers import ProviderError

        value, err = (value_err if value_err is not None
                      else (None, "external data: key not resolved"))
        if not err:
            return value
        if ph.failure_policy == "UseDefault":
            return ph.default
        if ph.failure_policy == "Ignore":
            return ph.original_value
        raise ProviderError(err)

    def _resolve_one(self, ph) -> Any:
        if self.provider_cache is None:
            # no providers configured: keep the original value semantics of
            # failurePolicy
            if ph.failure_policy == "UseDefault":
                return ph.default
            if ph.failure_policy == "Ignore":
                return ph.original_value
            raise RuntimeError(
                f"external data provider {ph.provider!r} unavailable"
            )
        return self.provider_cache.resolve(ph)
