"""Core mutate walk: apply a setter along a parsed location path.

Reference: pkg/mutation/mutators/core/mutation_function.go:26-239 — recursive
walk/update of the unstructured tree, creating missing nodes, keyed-list
match/merge with key-invariance, glob fan-out, and path-test gating
(path/tester: MustExist / MustNotExist at path prefixes).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from gatekeeper_tpu.mutation.path_parser import ListNode, ObjectNode

MUST_EXIST = "MustExist"
MUST_NOT_EXIST = "MustNotExist"


class MutateError(Exception):
    pass


class PathTester:
    """Path-prefix conditions (reference: path/tester/tester.go)."""

    def __init__(self, tests: Sequence[tuple] = ()):  # [(depth, condition)]
        self._by_depth = {}
        for depth, cond in tests:
            self._by_depth[depth] = cond

    def exists_ok(self, depth: int) -> bool:
        """May the walk proceed given the node at ``depth`` exists?"""
        return self._by_depth.get(depth) != MUST_NOT_EXIST

    def missing_ok(self, depth: int) -> bool:
        """May the walk create/continue given the node is missing?"""
        return self._by_depth.get(depth) != MUST_EXIST


class Setter:
    """Terminal-node behavior of a mutator (reference: core/setter.go)."""

    def set_value(self, parent: Any, key: Any, current: Any, exists: bool):
        """Returns (new_value, do_set)."""
        raise NotImplementedError


def mutate(obj: dict, path, setter: Setter,
           tester: Optional[PathTester] = None) -> bool:
    """Apply ``setter`` at ``path`` on ``obj`` in place; returns changed?"""
    tester = tester or PathTester()
    return _mutate(obj, path, 0, setter, tester)


def _mutate(node: Any, path, depth: int, setter: Setter,
            tester: PathTester) -> bool:
    part = path[depth]
    last = depth == len(path) - 1

    if isinstance(part, ObjectNode):
        if not isinstance(node, dict):
            raise MutateError(
                f"expected object at {part.name!r}, got {type(node).__name__}"
            )
        exists = part.name in node
        if exists and not tester.exists_ok(depth):
            return False
        if not exists and not tester.missing_ok(depth):
            return False
        if last:
            current = node.get(part.name)
            new, do_set = setter.set_value(node, part.name, current, exists)
            if do_set:
                if exists and _deep_equal(current, new):
                    return False
                node[part.name] = new
                return True
            return False
        if not exists:
            # create the missing intermediate (object or list, depending on
            # what the next path part needs — mutation_function.go:100-120)
            nxt = path[depth + 1]
            node[part.name] = [] if isinstance(nxt, ListNode) else {}
            changed = _mutate(node[part.name], path, depth + 1, setter, tester)
            if not changed:
                del node[part.name]  # undo speculative creation
            return changed
        return _mutate(node[part.name], path, depth + 1, setter, tester)

    # ListNode
    if not isinstance(node, list):
        raise MutateError(
            f"expected list at [{part.key_field}: ...], got "
            f"{type(node).__name__}"
        )
    changed = False
    matched = False
    for item in node:
        if not isinstance(item, dict):
            continue
        if part.glob or _key_match(item.get(part.key_field), part.key_value):
            matched = True
            if not tester.exists_ok(depth):
                continue
            if last:
                changed |= _set_list_item(node, item, part, setter)
            else:
                changed |= _mutate(item, path, depth + 1, setter, tester)
    if not matched and not part.glob:
        if not tester.missing_ok(depth):
            return False
        # create the keyed item (mutation_function.go keyed-list add)
        item = {part.key_field: _key_value(part)}
        if last:
            new, do_set = setter.set_value(None, None, None, False)
            if do_set:
                if isinstance(new, dict):
                    merged = dict(new)
                    if part.key_field in merged and not _key_match(
                        merged[part.key_field], part.key_value
                    ):
                        raise MutateError(
                            "key conflict: value changes the list key "
                            f"{part.key_field!r}"
                        )
                    merged.setdefault(part.key_field, _key_value(part))
                    node.append(merged)
                    return True
                raise MutateError(
                    "cannot assign non-object to keyed list item"
                )
            return False
        node.append(item)
        sub_changed = _mutate(item, path, depth + 1, setter, tester)
        if not sub_changed:
            node.remove(item)
        return sub_changed
    return changed


def _set_list_item(parent_list, item, part, setter) -> bool:
    new, do_set = setter.set_value(parent_list, item, item, True)
    if not do_set:
        return False
    if not isinstance(new, dict):
        raise MutateError("cannot assign non-object to keyed list item")
    if part.key_field in new and not part.glob and not _key_match(
        new[part.key_field], part.key_value
    ):
        raise MutateError(
            f"key conflict: value changes the list key {part.key_field!r}"
        )
    if _deep_equal(item, new):
        return False
    item.clear()
    item.update(new)
    return True


def _key_value(part: ListNode):
    v = part.key_value
    # numeric keys appear as strings in the DSL; keep string form (the
    # reference compares against the unstructured value with DeepEqual after
    # JSON round-trip, where keys are strings unless the field is numeric)
    return v


def _key_match(actual, expected) -> bool:
    if actual == expected:
        return True
    # numeric key fields: "8080" in the path matches 8080 in the object
    if isinstance(actual, (int, float)) and isinstance(expected, str):
        try:
            return float(expected) == float(actual)
        except ValueError:
            return False
    return False


def _deep_equal(a, b) -> bool:
    """Structural equality distinguishing bool from number (Python's
    True == 1 would otherwise mask real changes)."""
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _deep_equal(v, b[k]) for k, v in a.items()
        )
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(
            _deep_equal(x, y) for x, y in zip(a, b)
        )
    return a == b
