"""Device path-match kernel for mutation (BASELINE config #4).

The reference walks every (mutator, object) pair through the recursive
mutate function (pkg/mutation/mutators/core/mutation_function.go:26-239).
Here a parsed location path lowers to a fixed-depth index program over the
flattened token columns — the same predicate IR the verdict kernels use —
answering, per (mutator, object), "would the host walk CHANGE this
object?" as one [M, N] device grid.  The convergence loop (and the actual
tree surgery) stays host-side: the grid is the mass prefilter that keeps
the per-object Python walk off the no-op pairs.

Supported fragment (compile-or-fallback, like template lowering):
- Assign with a literal scalar value (no assignIf / fromMetadata /
  externalData), location = object nodes with at most ONE list node
  (glob ``[k: *]`` or string-keyed ``[k: v]``);
- AssignMetadata (labels/annotations keys, add-only semantics);
- no path tests (MustExist / MustNotExist).

Everything else returns None → the host walk is authoritative.  Parity
with ``core.mutate`` is asserted by tests/test_mutation_device.py
(including the walk's error outcomes — traversing a non-map — which
count as "no change").
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from gatekeeper_tpu.ir import nodes as N
from gatekeeper_tpu.ir.program import CompiledProgram, build_param_table
from gatekeeper_tpu.mutation.path_parser import ListNode, ObjectNode
from gatekeeper_tpu.ops.flatten import (Axis, Flattener, K_FALSE, K_MAP,
                                        K_NULL, K_NUM, K_OTHER, K_TRUE,
                                        RaggedCol, ScalarCol, Schema)

_TRUE = N.ConstBool(True)
_FALSE = N.ConstBool(False)


def _and(*terms):
    flat = [t for t in terms if t is not _TRUE]
    if any(t is _FALSE for t in flat):
        return _FALSE
    if not flat:
        return _TRUE
    return flat[0] if len(flat) == 1 else N.And(tuple(flat))


def _or(*terms):
    flat = [t for t in terms if t is not _FALSE]
    if any(t is _TRUE for t in flat):
        return _TRUE
    if not flat:
        return _FALSE
    return flat[0] if len(flat) == 1 else N.Or(tuple(flat))


class _PathLowerer:
    def __init__(self, vocab):
        self.vocab = vocab
        self.schema = Schema()

    def _scol(self, path: tuple) -> ScalarCol:
        col = ScalarCol(path)
        if col not in self.schema.scalars:
            self.schema.scalars.append(col)
        return col

    def _rcol(self, axis: Axis, subpath: tuple) -> RaggedCol:
        col = RaggedCol(axis, subpath)
        if col not in self.schema.raggeds:
            self.schema.raggeds.append(col)
        return col

    def _prefix_ok(self, col_of, parts: tuple) -> N.Expr:
        """Every present proper prefix must be a map (a present non-map
        intermediate makes the walk ERROR → no change)."""
        gates = []
        for i in range(1, len(parts)):
            col = col_of(parts[:i])
            gates.append(_or(N.Not(N.Present(col)), N.KindIs(col, K_MAP)))
        return _and(*gates)

    def _equal(self, col_of, path: tuple, value) -> N.Expr:
        """deep_equal(current, value) for a literal scalar ``value``
        (bools never equal numbers — core._deep_equal)."""
        col = col_of(path)
        if isinstance(value, bool):
            return N.KindIs(col, K_TRUE if value else K_FALSE)
        if value is None:
            return N.KindIs(col, K_NULL)
        if isinstance(value, str):
            return N.EqStr(N.FeatSid(col),
                           N.ConstSid(self.vocab.intern(value)))
        if isinstance(value, (int, float)):
            return _and(N.KindIs(col, K_NUM),
                        N.CmpNum(N.FeatNum(col), "eq",
                                 N.ConstNum(float(value))))
        raise ValueError(f"non-scalar value {value!r}")

    def lower(self, path, value, add_only: bool) -> tuple:
        """(change, error) predicates for one mutator's location path —
        change ⇔ the walk mutates; error ⇔ the walk raises MutateError
        (a present non-map intermediate / non-list at a list node).  The
        two are disjoint: an error aborts and rolls back."""
        list_idx = [i for i, p in enumerate(path)
                    if isinstance(p, ListNode)]
        if len(list_idx) > 1:
            raise ValueError("multiple list nodes")
        if not isinstance(value, (str, int, float, bool, type(None))):
            raise ValueError("non-scalar assign value")

        if not list_idx:
            parts = tuple(p.name for p in path)
            col_of = self._scol
            ok = self._prefix_ok(col_of, parts)
            leaf = col_of(parts)
            if add_only:
                change = _and(ok, N.Not(N.Present(leaf)))
            else:
                change = _and(ok, N.Not(self._equal(col_of, parts, value)))
            return change, N.Not(ok)

        g = list_idx[0]
        node: ListNode = path[g]
        if node.key_value is not None and not isinstance(
                node.key_value, str):
            raise ValueError("non-string list key")
        outer = tuple(p.name for p in path[:g])
        rest = tuple(p.name for p in path[g + 1:])
        if not rest:
            raise ValueError("list node is the path leaf (item assign)")
        if not outer:
            raise ValueError("list node at the path root")

        outer_ok = self._prefix_ok(self._scol, outer)
        list_col = self._scol(outer)
        axis = Axis(((outer,),))
        self._rcol(axis, ())  # materialize the axis counts

        def icol_of(parts: tuple) -> RaggedCol:
            return self._rcol(axis, parts)

        item_is_map = N.KindIs(icol_of(()), K_MAP)
        item_ok = self._prefix_ok(icol_of, rest)
        if add_only:
            item_change = N.Not(N.Present(icol_of(rest)))
        else:
            item_change = N.Not(self._equal(icol_of, rest, value))
        per_item = _and(item_is_map, item_ok, item_change)
        bad_list = _and(N.Present(list_col),
                        N.Not(N.KindIs(list_col, K_OTHER)))

        if node.glob:
            # glob never creates (absent/non-list/empty → no change); ANY
            # traversed item hitting a present non-map intermediate ERRORS
            # the whole walk — the system rolls back, so nothing changes
            any_err = N.AnyAxis(axis, _and(item_is_map, N.Not(item_ok)))
            err = _or(N.Not(outer_ok), bad_list, any_err)
            change = _and(outer_ok, N.KindIs(list_col, K_OTHER),
                          N.AnyAxis(axis, per_item), N.Not(any_err))
            return change, err

        key_eq = N.EqStr(N.FeatSid(icol_of((node.key_field,))),
                         N.ConstSid(self.vocab.intern(node.key_value)))
        matched_change = N.AnyAxis(axis, _and(item_is_map, key_eq,
                                              item_ok, item_change))
        matched_err = N.AnyAxis(axis, _and(item_is_map, key_eq,
                                           N.Not(item_ok)))
        no_match = N.Not(N.AnyAxis(axis, _and(item_is_map, key_eq)))
        # missing keyed item: the walk creates it and sets the leaf →
        # always a change (add-only too — the fresh leaf is absent);
        # an absent list is created the same way, a present NON-list errors
        list_ok = _or(N.Not(N.Present(list_col)),
                      N.KindIs(list_col, K_OTHER))
        err = _or(N.Not(outer_ok), bad_list, matched_err)
        change = _and(outer_ok, list_ok, _or(matched_change, no_match),
                      N.Not(matched_err))
        return change, err


class MutationPrefilter:
    """[M, N] would-change grids for a set of lowerable mutators.

    ``flatten_lane`` selects the columnizer the grids run over
    (``ops.flatten.FLATTEN_LANES``): ``auto`` takes the raw-bytes
    threaded C lane when the caller hands RawJSON objects over (the
    ``--mutate-ingest raw`` burst path) and the dict walker otherwise;
    ``differential`` runs raw THEN dict per batch and asserts the
    columns bit-identical (the ingest-lane proof)."""

    def __init__(self, vocab=None, flatten_lane: str = "auto"):
        from gatekeeper_tpu.ops.flatten import Vocab

        self.vocab = vocab if vocab is not None else Vocab()
        self.flatten_lane = flatten_lane
        self._programs: dict = {}  # id -> (CompiledProgram, schema)
        self._unsupported: dict = {}  # id -> reason

    def add_mutator(self, mutator) -> bool:
        """Compile one mutator's path program; False → host-only."""
        key = mutator.id
        try:
            value = getattr(mutator, "value", None)
            if mutator.kind == "Assign":
                if getattr(mutator, "assign_if", None):
                    raise ValueError("assignIf")
                if getattr(mutator, "from_metadata", None) is not None \
                        or getattr(mutator, "external", None) is not None:
                    raise ValueError("fromMetadata/externalData")
                add_only = False
            elif mutator.kind == "AssignMetadata":
                add_only = True
            else:
                raise ValueError(f"kind {mutator.kind}")
            if getattr(mutator, "tester", None) is not None and \
                    getattr(mutator.tester, "_by_depth", None):
                raise ValueError("path tests")
            if isinstance(value, (int, float)) and \
                    not isinstance(value, bool) and \
                    float(np.float32(value)) != float(value):
                # device equality compares float32 columns: a value that
                # doesn't round-trip f32 exactly could report "equal"
                # (no change) where the host's exact compare mutates —
                # keep such mutators host-authoritative
                raise ValueError("non-float32-exact numeric value")
            low = _PathLowerer(self.vocab)
            change, err = low.lower(mutator.path, value, add_only)
            self._programs[key] = (
                CompiledProgram(N.Program(
                    template_kind=f"mutator:{key}", expr=change,
                    params=(), schema=low.schema)),
                CompiledProgram(N.Program(
                    template_kind=f"mutator-err:{key}", expr=err,
                    params=(), schema=low.schema)),
                # change ∨ error in ONE program: the batched lane's
                # relevance test needs only this grid per mutator; the
                # err split runs lazily for mutators that actually have
                # relevant objects (halves the per-burst program runs)
                CompiledProgram(N.Program(
                    template_kind=f"mutator-rel:{key}",
                    expr=_or(change, err),
                    params=(), schema=low.schema)),
            )
            self._unsupported.pop(key, None)
            return True
        except (ValueError, Exception) as e:  # noqa: BLE001 — fallback
            self._programs.pop(key, None)
            self._unsupported[key] = str(e)
            return False

    def lowered_ids(self) -> list:
        return sorted(self._programs, key=str)

    def unsupported(self) -> dict:
        return dict(self._unsupported)

    def _grids(self, mutators: Sequence, objects: Sequence[dict],
               which: int, pad_n: Optional[int] = None) -> np.ndarray:
        n = len(objects)
        out = np.zeros((len(mutators), n), bool)
        todo = [(mi, self._programs[m.id][which])
                for mi, m in enumerate(mutators)
                if m.id in self._programs]
        if not todo or n == 0:
            return out
        schema = Schema()
        for _mi, prog in todo:
            schema.merge(prog.program.schema)
        pad = pad_n or max(8, 1 << (n - 1).bit_length())
        batch = Flattener(schema, self.vocab,
                          lane=self.flatten_lane).flatten(
            objects, pad_n=pad)
        for mi, prog in todo:
            table = build_param_table(prog.program, [_NoParams()],
                                      self.vocab)
            grid = prog.run(batch, table, vocab=self.vocab)
            out[mi] = grid[0, :n]
        return out

    def grids_and_batch(self, mutators: Sequence, objects: Sequence[dict],
                        pad_n: Optional[int] = None) -> tuple:
        """(change [M, N], error [M, N], ColumnBatch) with ONE flatten —
        the batched mutation lane's entry point: change/error programs
        run over a shared columnize pass, and the host-side batch stays
        available for columnar patch emission (presence/kind reads)."""
        n = len(objects)
        change = np.zeros((len(mutators), n), bool)
        err = np.zeros((len(mutators), n), bool)
        todo = [(mi, m) for mi, m in enumerate(mutators)
                if m.id in self._programs]
        if not todo or n == 0:
            return change, err, None
        schema = Schema()
        for _mi, m in todo:
            for prog in self._programs[m.id]:
                schema.merge(prog.program.schema)
        pad = pad_n or max(8, 1 << (n - 1).bit_length())
        batch = Flattener(schema, self.vocab,
                          lane=self.flatten_lane).flatten(
            objects, pad_n=pad)
        for mi, m in todo:
            change[mi] = self._run_on_batch(m, 0, batch, n)
            err[mi] = self._run_on_batch(m, 1, batch, n)
        return change, err, batch

    def _run_on_batch(self, mutator, which: int, batch, n: int):
        """One program row ([N] bool) over an already-flattened batch.

        Mutator predicate programs are tiny (a handful of presence/kind/
        equality gates); at webhook-burst sizes the jitted jax dispatch
        costs ~100x the arithmetic, so a direct numpy interpretation of
        the SAME expr tree is the fast path — semantics mirror
        ir/program.py:eval_expr for the fragment node set, and any node
        outside it falls back to the compiled program (differential
        parity is pinned either way)."""
        prog = self._programs[mutator.id][which]
        try:
            out = _np_eval(prog.program.expr, batch, n)
        except _NpUnsupported:
            table = build_param_table(prog.program, [_NoParams()],
                                      self.vocab)
            return prog.run(batch, table, vocab=self.vocab)[0, :n]
        return np.broadcast_to(np.asarray(out, bool), (n,))

    def relevance_and_batch(self, mutators: Sequence,
                            objects: Sequence[dict],
                            pad_n: Optional[int] = None) -> tuple:
        """(change∨error [M, N], ColumnBatch) with ONE flatten — the
        batched mutation lane's entry point: ONE combined relevance
        program runs per mutator over a shared columnize pass, and the
        host-side batch stays available for columnar patch emission
        (presence/kind reads) and the lazy error split
        (:meth:`error_row`)."""
        n = len(objects)
        rel = np.zeros((len(mutators), n), bool)
        todo = [(mi, m) for mi, m in enumerate(mutators)
                if m.id in self._programs]
        if not todo or n == 0:
            return rel, None
        schema = Schema()
        for _mi, m in todo:
            for prog in self._programs[m.id]:
                schema.merge(prog.program.schema)
        pad = pad_n or max(8, 1 << (n - 1).bit_length())
        batch = Flattener(schema, self.vocab,
                          lane=self.flatten_lane).flatten(
            objects, pad_n=pad)
        for mi, m in todo:
            rel[mi] = self._run_on_batch(m, 2, batch, n)
        return rel, batch

    def error_row(self, mutator, batch, n: int):
        """[N] bool error row over the shared batch (lazy: only runs
        for mutators that actually have relevant objects)."""
        return self._run_on_batch(mutator, 1, batch, n)

    def would_change(self, mutators: Sequence, objects: Sequence[dict],
                     pad_n: Optional[int] = None) -> np.ndarray:
        """[M, N] bool: grid[m, n] ⇔ the host walk would change object n
        with mutator m (rows for non-lowered mutators are False —
        callers route those through the host walk)."""
        return self._grids(mutators, objects, 0, pad_n)

    def would_error(self, mutators: Sequence, objects: Sequence[dict],
                    pad_n: Optional[int] = None) -> np.ndarray:
        """[M, N] bool: the host walk would raise MutateError (present
        non-map intermediate, non-list at a list node)."""
        return self._grids(mutators, objects, 1, pad_n)


class _NoParams:
    """Parameter-less constraint stand-in for build_param_table."""

    parameters: dict = {}


class _NpUnsupported(Exception):
    """Expr node outside the numpy fast path's fragment."""


def _np_eval(expr, batch, n: int):
    """Numpy interpretation of a mutator predicate over a host-side
    ColumnBatch — the node-for-node mirror of eval_expr (ir/program.py)
    restricted to the fragment _PathLowerer emits: ConstBool / And / Or
    / Not / Present / KindIs / EqStr(FeatSid, ConstSid) /
    CmpNum(eq, FeatNum, ConstNum) / AnyAxis."""

    def feat(col, field, in_axis):
        store = batch.raggeds if isinstance(col, RaggedCol) \
            else batch.scalars
        c = store.get(col)
        if c is None:
            raise _NpUnsupported(str(col))
        a = getattr(c, field)[:n]
        if in_axis and not isinstance(col, RaggedCol):
            a = a[:, None]  # _expand_for_ctx: scalar under an axis
        return a

    def sidlike(e, in_axis):
        if isinstance(e, N.FeatSid):
            kind = feat(e.col, "kind", in_axis)
            return feat(e.col, "sid", in_axis), kind == 4  # K_STR
        if isinstance(e, N.ConstSid):
            return np.int32(e.sid), np.bool_(True)
        raise _NpUnsupported(type(e).__name__)

    def ev(e, in_axis):
        if isinstance(e, N.ConstBool):
            return np.bool_(e.value)
        if isinstance(e, N.Not):
            return np.logical_not(ev(e.inner, in_axis))
        if isinstance(e, N.And):
            out = None
            for t in e.terms:
                v = ev(t, in_axis)
                out = v if out is None else out & v
            return out if out is not None else np.bool_(True)
        if isinstance(e, N.Or):
            out = None
            for t in e.terms:
                v = ev(t, in_axis)
                out = v if out is None else out | v
            return out if out is not None else np.bool_(False)
        if isinstance(e, N.Present):
            return feat(e.col, "kind", in_axis) > 0
        if isinstance(e, N.KindIs):
            return feat(e.col, "kind", in_axis) == e.kind
        if isinstance(e, N.EqStr):
            if e.negate:
                raise _NpUnsupported("EqStr negate")
            lv, lok = sidlike(e.lhs, in_axis)
            rv, rok = sidlike(e.rhs, in_axis)
            return lok & rok & (lv == rv)
        if isinstance(e, N.CmpNum):
            if e.op != "eq" or not isinstance(e.lhs, N.FeatNum) or \
                    not isinstance(e.rhs, N.ConstNum):
                raise _NpUnsupported("CmpNum")
            kind = feat(e.lhs.col, "kind", in_axis)
            num = feat(e.lhs.col, "num", in_axis)
            return (kind == K_NUM) & (num == np.float32(e.rhs.value))
        if isinstance(e, N.AnyAxis):
            if in_axis:
                raise _NpUnsupported("nested AnyAxis")
            counts = batch.axis_counts.get(e.axis)
            if counts is None:
                raise _NpUnsupported(str(e.axis))
            counts = counts[:n]
            inner = ev(e.inner, True)
            if getattr(inner, "ndim", 0) < 2:
                return np.asarray(inner) & (counts > 0)
            m = inner.shape[1]
            valid = np.arange(m) < counts[:, None]
            return np.any(inner & valid, axis=1)
        raise _NpUnsupported(type(e).__name__)

    return ev(expr, False)
