from gatekeeper_tpu.apis.templates import (  # noqa: F401
    CodeEntry,
    ConstraintTemplate,
    TemplateTarget,
)
from gatekeeper_tpu.apis.constraints import Constraint  # noqa: F401
