"""ConstraintTemplate API types.

Reference shape: /root/reference/apis/templates/v1beta1 (ConstraintTemplate CRD):
``spec.crd.spec.names.kind`` names the generated constraint kind,
``spec.crd.spec.validation.openAPIV3Schema`` schemas the ``parameters`` field,
``spec.targets[]`` carries per-target policy source — legacy ``rego`` (+``libs``)
or the multi-engine ``code: [{engine, source}]`` list (v1beta1 types; consumed at
/root/reference/pkg/webhook/policy.go:419-427).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.utils.unstructured import deep_get

# Engine names (reference: "Rego" legacy field; k8scel engine name
# "K8sNativeValidation" in pkg/drivers/k8scel/schema/schema.go).
ENGINE_REGO = "Rego"
ENGINE_CEL = "K8sNativeValidation"


@dataclass
class CodeEntry:
    engine: str
    source: Any  # engine-specific blob


@dataclass
class TemplateTarget:
    target: str
    rego: str = ""
    libs: list[str] = field(default_factory=list)
    code: list[CodeEntry] = field(default_factory=list)

    def source_for(self, engine: str) -> Optional[Any]:
        for entry in self.code:
            if entry.engine == engine:
                return entry.source
        if engine == ENGINE_REGO and self.rego:
            return {"rego": self.rego, "libs": self.libs}
        return None


class TemplateError(Exception):
    """Invalid ConstraintTemplate (reference: webhook template validation,
    pkg/webhook/policy.go:359-401)."""


@dataclass
class ConstraintTemplate:
    name: str
    kind: str  # generated constraint kind, e.g. K8sRequiredLabels
    targets: list[TemplateTarget]
    parameters_schema: Optional[dict] = None
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_unstructured(obj: dict) -> "ConstraintTemplate":
        if obj.get("kind") != "ConstraintTemplate":
            raise TemplateError(f"not a ConstraintTemplate: kind={obj.get('kind')!r}")
        api_version = obj.get("apiVersion", "") or ""
        if not api_version.startswith("templates.gatekeeper.sh/"):
            raise TemplateError(
                f"template group must be templates.gatekeeper.sh, got {api_version!r}"
            )
        name = deep_get(obj, ("metadata", "name"), "")
        if not name:
            raise TemplateError("template has no metadata.name")
        kind = deep_get(obj, ("spec", "crd", "spec", "names", "kind"), "")
        if not kind:
            raise TemplateError(f"template {name}: missing spec.crd.spec.names.kind")
        # Reference requires the template name to equal the lowercased kind
        # (framework CreateCRD validation).
        if name != kind.lower():
            raise TemplateError(
                f"template name {name!r} must be the lowercase of kind {kind!r}"
            )
        schema = deep_get(
            obj, ("spec", "crd", "spec", "validation", "openAPIV3Schema"), None
        )
        targets = []
        for t in deep_get(obj, ("spec", "targets"), []) or []:
            code = [
                CodeEntry(engine=c.get("engine", ""), source=c.get("source"))
                for c in t.get("code", []) or []
            ]
            targets.append(
                TemplateTarget(
                    target=t.get("target", ""),
                    rego=t.get("rego", "") or "",
                    libs=list(t.get("libs", []) or []),
                    code=code,
                )
            )
        if not targets:
            raise TemplateError(f"template {name}: no targets")
        if len(targets) > 1:
            raise TemplateError(f"template {name}: multiple targets unsupported")
        return ConstraintTemplate(
            name=name,
            kind=kind,
            targets=targets,
            parameters_schema=schema,
            labels=deep_get(obj, ("metadata", "labels"), {}) or {},
            annotations=deep_get(obj, ("metadata", "annotations"), {}) or {},
            raw=obj,
        )

    def constraint_crd(self) -> dict:
        """Synthesize the constraint CRD for this template.

        Reference: framework ``Client.CreateCRD`` builds a CRD under group
        ``constraints.gatekeeper.sh`` with the template's kind and the
        parameters schema nested under ``spec.parameters`` plus the shared
        ``spec.match`` schema (pkg/target/matchcrd_constant.go).
        """
        params = self.parameters_schema or {"type": "object"}
        return {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": f"{self.name}.constraints.gatekeeper.sh"},
            "spec": {
                "group": "constraints.gatekeeper.sh",
                "names": {"kind": self.kind, "listKind": self.kind + "List",
                          "plural": self.name, "singular": self.name},
                "scope": "Cluster",
                "versions": [
                    {
                        "name": "v1beta1",
                        "served": True,
                        "storage": True,
                        "schema": {
                            "openAPIV3Schema": {
                                "type": "object",
                                "properties": {
                                    "spec": {
                                        "type": "object",
                                        "properties": {
                                            "match": {"type": "object"},
                                            "parameters": params,
                                            "enforcementAction": {"type": "string"},
                                            "scopedEnforcementActions": {
                                                "type": "array"
                                            },
                                        },
                                    },
                                    "status": {"type": "object"},
                                },
                            }
                        },
                    }
                ],
            },
        }
