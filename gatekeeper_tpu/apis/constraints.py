"""Constraint wrapper + enforcement-action semantics.

Constraints are *dynamic* objects (instances of the CRD a template generates,
group ``constraints.gatekeeper.sh``).  This module wraps the unstructured form
and implements the enforcement-action model of
/root/reference/pkg/util/enforcement_action.go:16-170:

- actions: ``deny`` (default), ``dryrun``, ``warn``, ``scoped``
- ``scoped`` defers to ``spec.scopedEnforcementActions[]``, each entry naming an
  action plus the enforcement points (webhook / audit / gator / vap / ``*``)
  it applies to.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of, labels_of, name_of

CONSTRAINTS_GROUP = "constraints.gatekeeper.sh"

# Enforcement actions (reference: util/enforcement_action.go:16-24).
DENY = "deny"
DRYRUN = "dryrun"
WARN = "warn"
SCOPED = "scoped"
KNOWN_ACTIONS = (DENY, DRYRUN, WARN, SCOPED)

# Enforcement points (reference: util/enforcement_action.go:26-41).
WEBHOOK_EP = "validation.gatekeeper.sh"
AUDIT_EP = "audit.gatekeeper.sh"
GATOR_EP = "gator.gatekeeper.sh"
VAP_EP = "vap.k8s.io"
ALL_EP = "*"
KNOWN_EPS = (WEBHOOK_EP, AUDIT_EP, GATOR_EP, VAP_EP)


class ConstraintError(Exception):
    pass


@dataclass
class Constraint:
    kind: str
    name: str
    match: dict
    parameters: Any
    enforcement_action: str
    scoped_actions: list[dict] = field(default_factory=list)
    labels: dict = field(default_factory=dict)
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_unstructured(obj: dict) -> "Constraint":
        group, _, kind = gvk_of(obj)
        if group != CONSTRAINTS_GROUP:
            raise ConstraintError(
                f"constraint group must be {CONSTRAINTS_GROUP}, got {group!r}"
            )
        action = deep_get(obj, ("spec", "enforcementAction"), DENY) or DENY
        scoped = deep_get(obj, ("spec", "scopedEnforcementActions"), None)
        if action == SCOPED and not scoped:
            raise ConstraintError(
                "scoped enforcementAction requires spec.scopedEnforcementActions"
            )
        if action != SCOPED and scoped:
            # Reference: scopedEnforcementActions only honored with action scoped
            # (webhook validation, policy.go:443-452).
            raise ConstraintError(
                "spec.scopedEnforcementActions requires enforcementAction: scoped"
            )
        name = name_of(obj)
        if not name:
            raise ConstraintError("constraint has no metadata.name")
        c = Constraint(
            kind=kind,
            name=name,
            match=deep_get(obj, ("spec", "match"), {}) or {},
            parameters=deep_get(obj, ("spec", "parameters"), None),
            enforcement_action=action,
            scoped_actions=list(scoped or []),
            labels=labels_of(obj),
            raw=obj,
        )
        c.validate_actions()
        return c

    def validate_actions(self) -> None:
        # Reference: GetEnforcementAction maps unknown actions to Unrecognized
        # and ValidateScopedEnforcementAction rejects empty/unknown enforcement
        # points (util/enforcement_action.go:43-107).
        if self.enforcement_action not in KNOWN_ACTIONS:
            raise ConstraintError(
                f"unrecognized enforcementAction {self.enforcement_action!r}"
            )
        for entry in self.scoped_actions:
            if entry.get("action") not in (DENY, DRYRUN, WARN):
                raise ConstraintError(
                    f"unrecognized scoped action {entry.get('action')!r}"
                )
            eps = entry.get("enforcementPoints")
            if not eps:
                raise ConstraintError(
                    "scopedEnforcementActions entry has no enforcementPoints"
                )
            for ep in eps:
                ep_name = ep.get("name", "") if isinstance(ep, dict) else str(ep)
                if ep_name != ALL_EP and ep_name not in KNOWN_EPS:
                    raise ConstraintError(
                        f"unrecognized enforcement point {ep_name!r}"
                    )

    def actions_for(self, enforcement_point: str) -> list[str]:
        """Resolve the action list applicable at an enforcement point.

        Reference: util/enforcement_action.go:109-170 (scoped resolution).
        A non-scoped constraint yields its single action at every point; a
        scoped constraint yields the actions whose enforcementPoints include
        the point (or ``*``).
        """
        if self.enforcement_action != SCOPED:
            return [self.enforcement_action]
        out: list[str] = []
        for entry in self.scoped_actions:
            action = entry.get("action", DENY)
            # Missing/empty enforcementPoints = never enabled (reference:
            # enforcementPointEnabled returns false for an empty list).
            eps = entry.get("enforcementPoints") or []
            for ep in eps:
                ep_name = ep.get("name", "") if isinstance(ep, dict) else str(ep)
                if ep_name in (ALL_EP, enforcement_point) and action not in out:
                    out.append(action)
        return out

    def key(self) -> tuple[str, str]:
        return (self.kind, self.name)
