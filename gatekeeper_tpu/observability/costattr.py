"""Per-template / per-constraint cost attribution.

"Which policy makes admission slow" should be a query, not a guess.  The
batched lanes deliberately evaluate MANY templates in one fused device
pass (`device.query_batch`, `device.sweep_dispatch`), so no single
template ever owns a span — this module apportions each shared pass's
wall time across the constraint grid:

- **dispatch/query time** splits by *row occupancy*: the number of
  (constraint, object) cells of each template's match mask that were
  actually live in the pass (a template matching every Pod in a 10k-row
  chunk carries more of the pass than one matching three ConfigMaps).
- **flatten/columnize time** splits across the templates whose schemas
  the union flatten served, weighted by constraint count (columns are
  schema-driven; rows are shared).
- **render time** (the exact-interpreter message rendering of device
  hits) is attributed *exactly* — each render call is timed and charged
  to its constraint's template.

Every apportionment distributes the measured wall time completely, so
per-template `gatekeeper_constraint_eval_seconds` sums reproduce the
parent span's wall time (the closure property the tests assert) and the
top entry of ``/debug/cost`` is the template to go look at.

Activation mirrors ``resilience/faults.py``: :func:`install` is the
process-global switch, :func:`activate` the scoped test variant,
:func:`active` the hot-path read (one global list read when off).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

# enforcement points (metric label values)
EP_WEBHOOK = "webhook"
EP_AUDIT = "audit"
EP_MUTATION = "mutation"

# phases (metric label values)
PHASE_DISPATCH = "dispatch"
PHASE_FLATTEN = "flatten"
PHASE_RENDER = "render"
PHASE_APPLY = "apply"


class CostAttribution:
    """Accumulates apportioned wall seconds per (template,
    enforcement_point, phase); optionally mirrors into the metrics
    registry as `gatekeeper_constraint_eval_seconds`."""

    def __init__(self, metrics=None, max_templates: int = 512,
                 max_tenants: int = 512, max_clusters: int = 512):
        self.metrics = metrics
        self.max_templates = max_templates
        self.max_tenants = max_tenants
        self.max_clusters = max_clusters
        self._lock = threading.Lock()
        # (template, ep, phase) -> [seconds, passes, rows]
        self._cells: dict = {}
        # the {tenant} axis (observability NEXT #1): (tenant, ep) ->
        # [seconds, requests, admission cost].  Kept SEPARATE from the
        # template cells so the per-template closure property (shares
        # sum to the parent pass's wall) is untouched — tenant seconds
        # are request wall, a different population.
        self._tenant_cells: dict = {}
        # the {cluster} axis (fleet mode): (cluster, ep) -> [seconds,
        # passes, rows].  Same additive-cardinality contract as tenants
        # (templates + tenants + clusters, never their product): fleet
        # packed dispatches apportion their wall across the clusters
        # whose rows rode the batch, so "which cluster is expensive" is
        # a query even when every dispatch is shared.
        self._cluster_cells: dict = {}

    # --- recording -----------------------------------------------------
    def record(self, template: str, enforcement_point: str, phase: str,
               seconds: float, rows: int = 0) -> None:
        key = (template, enforcement_point, phase)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                if len(self._cells) >= self.max_templates * 4:
                    key = ("other", enforcement_point, phase)
                    cell = self._cells.get(key)
                if cell is None:
                    cell = self._cells[key] = [0.0, 0, 0]
            cell[0] += seconds
            cell[1] += 1
            cell[2] += rows
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.CONSTRAINT_EVAL,
                {"template": key[0], "enforcement_point": enforcement_point,
                 "phase": phase},
                value=seconds)

    def record_tenant(self, tenant: str, enforcement_point: str,
                      seconds: float, cost: float = 0.0) -> None:
        """One admission's wall seconds + admission cost charged to its
        tenant — the ``{tenant}`` axis on
        ``gatekeeper_constraint_eval_seconds``.  The metric rides
        separate series ``{tenant, enforcement_point, phase="admission"}``
        (no template label) so cardinality stays ADDITIVE (templates +
        tenants, not their product); past ``max_tenants`` new tenants
        fold into ``other`` here, and the registry's label-cardinality
        guard bounds the exposed series regardless."""
        key = (tenant, enforcement_point)
        with self._lock:
            cell = self._tenant_cells.get(key)
            if cell is None:
                if len(self._tenant_cells) >= self.max_tenants:
                    key = ("other", enforcement_point)
                    cell = self._tenant_cells.get(key)
                if cell is None:
                    cell = self._tenant_cells[key] = [0.0, 0, 0.0]
            cell[0] += seconds
            cell[1] += 1
            cell[2] += cost
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.CONSTRAINT_EVAL,
                {"tenant": key[0], "enforcement_point": enforcement_point,
                 "phase": "admission"},
                value=seconds)

    def record_cluster(self, cluster: str, enforcement_point: str,
                       seconds: float, rows: int = 0) -> None:
        """One cluster's share of a (possibly fleet-packed) pass —
        the ``{cluster}`` axis on ``gatekeeper_constraint_eval_seconds``
        (series ``{cluster, enforcement_point, phase="sweep"}``, no
        template label, additive cardinality).  Past ``max_clusters``
        new clusters fold into ``other`` here, and the registry's
        label-cardinality guard bounds the exposed series regardless."""
        key = (cluster, enforcement_point)
        with self._lock:
            cell = self._cluster_cells.get(key)
            if cell is None:
                if len(self._cluster_cells) >= self.max_clusters:
                    key = ("other", enforcement_point)
                    cell = self._cluster_cells.get(key)
                if cell is None:
                    cell = self._cluster_cells[key] = [0.0, 0, 0]
            cell[0] += seconds
            cell[1] += 1
            cell[2] += rows
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(
                M.CONSTRAINT_EVAL,
                {"cluster": key[0], "enforcement_point": enforcement_point,
                 "phase": "sweep"},
                value=seconds)

    def attribute_clusters(self, wall_s: float, rows: dict,
                           enforcement_point: str) -> None:
        """Apportion one packed pass's wall across ``rows``
        ({cluster: row count}) — shares sum to ``wall_s`` exactly, the
        same closure contract :meth:`attribute` keeps for templates."""
        if wall_s <= 0 or not rows:
            return
        total = float(sum(max(0, r) for r in rows.values()))
        n = len(rows)
        for cluster, r in rows.items():
            share = (wall_s * max(0, int(r)) / total) if total > 0 \
                else wall_s / n
            self.record_cluster(cluster, enforcement_point, share,
                                rows=int(r))

    def cluster_totals(self, enforcement_point: Optional[str] = None
                       ) -> dict:
        """{cluster: attributed seconds} — per-cluster cost roll-up."""
        out: dict = {}
        with self._lock:
            for (cluster, ep), (s, _n, _r) in self._cluster_cells.items():
                if enforcement_point is None or ep == enforcement_point:
                    out[cluster] = out.get(cluster, 0.0) + s
        return out

    def tenant_totals(self, enforcement_point: Optional[str] = None
                      ) -> dict:
        """{tenant: attributed seconds} — the "who is heaviest" input
        the QoS displacement ladder consumes
        (``OverloadController.set_tenant_cost_input``)."""
        out: dict = {}
        with self._lock:
            for (tenant, ep), (s, _n, _c) in self._tenant_cells.items():
                if enforcement_point is None or ep == enforcement_point:
                    out[tenant] = out.get(tenant, 0.0) + s
        return out

    def attribute(self, wall_s: float, weights: dict,
                  enforcement_point: str, phase: str,
                  rows: Optional[dict] = None) -> None:
        """Apportion ``wall_s`` across ``weights`` ({template: weight});
        the shares always sum to ``wall_s`` exactly (closure).  Zero or
        empty weights fall back to an even split."""
        if wall_s <= 0 or not weights:
            return
        total = float(sum(max(0.0, w) for w in weights.values()))
        n = len(weights)
        for template, w in weights.items():
            share = (wall_s * max(0.0, float(w)) / total) if total > 0 \
                else wall_s / n
            self.record(template, enforcement_point, phase, share,
                        rows=int((rows or {}).get(template, 0)))

    # --- introspection --------------------------------------------------
    def snapshot(self) -> dict:
        """The ``/debug/cost`` payload: raw cells plus a per-template
        roll-up sorted most-expensive-first."""
        with self._lock:
            cells = [
                {"template": t, "enforcement_point": ep, "phase": ph,
                 "seconds": round(s, 6), "passes": c, "rows": r}
                for (t, ep, ph), (s, c, r) in self._cells.items()
            ]
        by_template: dict = {}
        for cell in cells:
            agg = by_template.setdefault(
                cell["template"],
                {"template": cell["template"], "seconds": 0.0,
                 "passes": 0, "rows": 0, "phases": {}})
            agg["seconds"] = round(agg["seconds"] + cell["seconds"], 6)
            agg["passes"] += cell["passes"]
            agg["rows"] += cell["rows"]
            ph = agg["phases"]
            ph[cell["phase"]] = round(
                ph.get(cell["phase"], 0.0) + cell["seconds"], 6)
        top = sorted(by_template.values(),
                     key=lambda a: -a["seconds"])
        with self._lock:
            tenants = sorted(
                ({"tenant": t, "enforcement_point": ep,
                  "seconds": round(s, 6), "requests": n,
                  "admission_cost": round(c, 1)}
                 for (t, ep), (s, n, c) in self._tenant_cells.items()),
                key=lambda a: -a["seconds"])
            clusters = sorted(
                ({"cluster": cl, "enforcement_point": ep,
                  "seconds": round(s, 6), "passes": n, "rows": r}
                 for (cl, ep), (s, n, r) in self._cluster_cells.items()),
                key=lambda a: -a["seconds"])
        return {"top": top, "tenants": tenants, "clusters": clusters,
                "cells": sorted(cells, key=lambda c: -c["seconds"])}

    def total_seconds(self, enforcement_point: Optional[str] = None,
                      phase: Optional[str] = None) -> float:
        """Summed attributed seconds, optionally filtered — the closure
        check's left-hand side."""
        with self._lock:
            return sum(
                s for (t, ep, ph), (s, c, r) in self._cells.items()
                if (enforcement_point is None or ep == enforcement_point)
                and (phase is None or ph == phase))

    def table(self, limit: int = 15) -> str:
        """Human table for ``gator bench --attribution``."""
        snap = self.snapshot()
        rows = snap["top"][:limit]
        if not rows:
            return "cost attribution: (no passes recorded)"
        w = max([len("template")] + [len(r["template"]) for r in rows])
        lines = [f"{'template':<{w}}  {'seconds':>9}  {'passes':>6}  "
                 f"{'rows':>9}  phases"]
        for r in rows:
            phases = " ".join(
                f"{k}={v:.3f}" for k, v in sorted(r["phases"].items()))
            lines.append(f"{r['template']:<{w}}  {r['seconds']:>9.3f}  "
                         f"{r['passes']:>6}  {r['rows']:>9}  {phases}")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            self._tenant_cells.clear()
            self._cluster_cells.clear()


# --- activation (the faults.py pattern) -----------------------------------

_global: list = [None]


def install(attr: Optional[CostAttribution]) -> None:
    """Process-global activation (the CLI / serving entrypoint)."""
    _global[0] = attr


def uninstall() -> None:
    _global[0] = None


def active() -> Optional[CostAttribution]:
    """The hot-path read: one global list access; None = attribution off
    (call sites skip weight computation entirely)."""
    return _global[0]


@contextmanager
def activate(attr: CostAttribution):
    """Scoped activation for tests; restores the previous instance."""
    prev = _global[0]
    _global[0] = attr
    try:
        yield attr
    finally:
        _global[0] = prev
