"""Admission flight recorder: "why was THIS request shed at 14:02".

A bounded structured ring of every admission / mutation / shed decision
the serving path makes, with enough context to reconstruct the decision
after the fact: uid, verdict, matched-template messages (truncated),
lane, admission cost, trace id (the link into ``/debug/traces``), and
the overload state at decision time (brownout level, in-flight limit,
queue depth).  Served at ``/debug/decisions?uid=``; optionally mirrored
to a JSONL file sink (the ``export/`` seam's disk shape — one line per
decision, append-only, the operator's black box).

Privacy: the in-memory ring stores decision METADATA only — kind,
name, namespace, uid, messages — never the object body (admission
payloads carry Secrets).  Messages truncate at ``max_message``.  With
``capture=True`` the JSONL *sink* lines additionally carry the raw
admission ``request`` (the replay corpus for ``gator replay``); the
ring still never holds bodies, and capture is opt-in precisely because
the sink then holds Secrets-grade data.

Activation mirrors ``resilience/faults.py``: :func:`install` process-
global, :func:`activate` scoped for tests, :func:`active` the hot-path
read.  Recording is one dict build + deque append under a lock —
nanoseconds against a millisecond admission path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional


def _open_sink(path: str):
    """Append-open the JSONL sink, repairing a torn tail first.

    A recorder killed mid-write leaves a partial final line with no
    newline; appending straight after it would fuse the next record
    onto the fragment, corrupting BOTH lines for every reader.  Writing
    one separating newline confines the damage to the already-lost
    fragment (readers count it as a single truncated record)."""
    torn = False
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            if f.tell() > 0:
                f.seek(-1, 2)
                torn = f.read(1) != b"\n"
    except OSError:
        pass  # absent/unreadable: plain append-create below
    sink = open(path, "a", buffering=1)  # line-buffered
    if torn:
        try:
            sink.write("\n")
        except Exception:
            pass
    return sink


def rotated_paths(path: str) -> list:
    """Every existing file of a (possibly rotated) sink set, OLDEST
    first: ``path.N`` … ``path.1`` then ``path`` itself.  Readers
    (``gator decisions`` / ``gator triage`` offline mode) concatenate
    these to see the full retained decision stream; each file repairs /
    counts its own torn tail independently, so rotation never corrupts
    a read."""
    out: list = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    for i in range(n - 1, 0, -1):
        out.append(f"{path}.{i}")
    if os.path.exists(path):
        out.append(path)
    return out


class FlightRecorder:
    def __init__(self, capacity: int = 2048,
                 sink_path: Optional[str] = None,
                 metrics=None,
                 wall=time.time,
                 max_message: int = 512,
                 capture: bool = False,
                 sink_max_bytes: int = 0,
                 sink_keep: int = 3):
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self.metrics = metrics
        self._wall = wall
        self.max_message = max_message
        self.capture = capture
        self.recorded = 0
        self._sink = None
        self.sink_path = sink_path
        # size-based sink rotation (--flight-recorder-sink-max-mb): a
        # sink past sink_max_bytes rotates to path.1 (path.1 -> path.2
        # ... up to sink_keep rotated files, oldest dropped) and a
        # fresh sink opens.  0 = unbounded (the pre-rotation shape)
        self.sink_max_bytes = max(0, int(sink_max_bytes))
        self.sink_keep = max(1, int(sink_keep))
        self.rotations = 0
        self._sink_lock = threading.Lock()
        self._sink_bytes = 0
        if sink_path:
            self._sink = _open_sink(sink_path)
            try:
                self._sink_bytes = os.path.getsize(sink_path)
            except OSError:
                self._sink_bytes = 0

    # --- recording -----------------------------------------------------
    def record(self, endpoint: str, decision: str, uid: str = "",
               obj_kind: str = "", name: str = "", namespace: str = "",
               operation: str = "", message: str = "", lane: str = "",
               cost: float = 0.0, reason: str = "",
               warnings: int = 0, code: int = 0,
               overload=None, tenant: str = "", cluster: str = "",
               request=None, **extra) -> dict:
        """One decision.  ``endpoint``: validate|mutate; ``decision``:
        allow|deny|shed|error|deadline.  ``overload`` is the
        OverloadController whose state gets snapshotted (or None).
        ``tenant`` is the QoS/attribution tenant key (namespace or
        serviceaccount) — the axis ``?tenant=`` and ``gator decisions
        --tenant`` filter on.  ``cluster`` (fleet mode) names the
        serving cluster the decision belongs to — the ``?cluster=`` /
        ``gator decisions --cluster`` axis, so a fleet's interleaved
        decision stream stays attributable per cluster.  ``request``
        (capture mode only) is the raw admission request dict; it rides
        the sink line — never the ring — as the replay corpus."""
        from gatekeeper_tpu.observability import tracing

        span = tracing.current_span()
        entry = {
            "ts": self._wall(),
            "endpoint": endpoint,
            "decision": decision,
            "uid": uid,
            "kind": obj_kind,
            "name": name,
            "namespace": namespace,
        }
        if operation:
            entry["operation"] = operation
        if tenant:
            entry["tenant"] = tenant
        if cluster:
            entry["cluster"] = cluster
        if message:
            entry["message"] = message[: self.max_message]
        if lane:
            entry["lane"] = lane
        if cost:
            entry["cost"] = round(float(cost), 1)
        if reason:
            entry["reason"] = reason
        if warnings:
            entry["warnings"] = warnings
        if code:
            entry["code"] = code
        if span is not None and getattr(span, "trace_id", ""):
            entry["trace_id"] = span.trace_id
        if overload is not None:
            try:
                entry["overload"] = {
                    "brownout": overload.brownout_level(),
                    "inflight_limit": overload.limiter.limit,
                    "queue_depth": overload.queue_depth(),
                }
            except Exception:
                pass
        # targeted SLO degradations in force at decision time (the
        # overload-state change the degradation maps make visible in
        # the black box — "this allow served a stale namespace")
        try:
            from gatekeeper_tpu.resilience import overload as _ovl

            reg = _ovl.active_degradations()
            if reg is not None:
                degraded = reg.active_names()
                if degraded:
                    entry.setdefault("overload", {})["degraded"] = \
                        degraded
        except Exception:
            pass
        for k, v in extra.items():
            if v not in (None, "", 0):
                entry[k] = v
        with self._lock:
            self._ring.append(entry)
            self.recorded += 1
            sink = self._sink
        if sink is not None:
            line = entry
            if self.capture and request is not None:
                # bodies ride the sink only: the ring (served at
                # /debug/decisions) stays metadata-only
                line = dict(entry)
                line["request"] = request
            try:
                data = json.dumps(line, default=str) + "\n"
                with self._sink_lock:
                    sink = self._sink  # re-read: rotation swaps it
                    if sink is not None:
                        sink.write(data)
                        self._sink_bytes += len(data)
                        if self.sink_max_bytes and \
                                self._sink_bytes >= self.sink_max_bytes:
                            self._rotate_locked()
            except Exception:
                pass  # the recorder must never fail an admission
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.inc_counter(M.FLIGHTREC_DECISIONS,
                                     {"decision": decision})
        return entry

    # --- lookup ---------------------------------------------------------
    def by_uid(self, uid: str) -> list:
        with self._lock:
            return [e for e in self._ring if e.get("uid") == uid]

    def decisions(self, limit: int = 100) -> list:
        """Most recent first."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[: max(0, limit)]

    def snapshot(self, uid: Optional[str] = None,
                 limit: int = 100,
                 since: Optional[float] = None,
                 until: Optional[float] = None,
                 kinds: Optional[set] = None,
                 tenant: Optional[str] = None,
                 cluster: Optional[str] = None) -> dict:
        """The ``/debug/decisions`` payload.

        ``since``/``until`` bound the decision timestamp (unix seconds,
        half-open ``[since, until)``); ``kinds`` keeps only the named
        decision kinds (allow|deny|shed|error|deadline); ``tenant``
        keeps one tenant's decisions (the QoS/attribution axis);
        ``cluster`` keeps one cluster's decisions (the fleet axis).
        Filters compose with each other and with ``uid``, so "every
        shed tenant-a took between 14:02 and 14:03" is one query
        instead of a ring dump."""
        with self._lock:
            ring = list(self._ring)
        filtered = since is not None or until is not None or kinds \
            or tenant is not None or cluster is not None
        if filtered:
            ring = [e for e in ring
                    if (since is None or e.get("ts", 0.0) >= since)
                    and (until is None or e.get("ts", 0.0) < until)
                    and (not kinds or e.get("decision") in kinds)
                    and (tenant is None or e.get("tenant", "") == tenant)
                    and (cluster is None
                         or e.get("cluster", "") == cluster)]
        if uid:
            matched = [e for e in ring if e.get("uid") == uid]
            return {"uid": uid, "recorded": self.recorded,
                    **({"matched": len(matched)} if filtered else {}),
                    "decisions": matched}
        ring.reverse()
        out = {"recorded": self.recorded,
               "capacity": self._ring.maxlen,
               "sink": self.sink_path or "",
               "decisions": ring[: max(0, limit)]}
        if filtered:
            out["matched"] = len(ring)
        return out

    def _rotate_locked(self) -> None:
        """Shift the sink set one slot (call under ``_sink_lock``):
        close, ``path.k -> path.k+1`` newest-first (the file past
        ``sink_keep`` is dropped), ``path -> path.1``, reopen fresh.
        The shift preserves per-file line integrity, so torn-tail
        repair and readers work unchanged across the set."""
        path = self.sink_path
        try:
            self._sink.close()
        except Exception:
            pass
        self._sink = None
        try:
            drop = f"{path}.{self.sink_keep}"
            if os.path.exists(drop):
                os.remove(drop)
            for i in range(self.sink_keep - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            os.replace(path, f"{path}.1")
        except OSError:
            pass  # rotation best-effort: keep recording into `path`
        self._sink = _open_sink(path)
        self._sink_bytes = 0
        self.rotations += 1

    def close(self) -> None:
        with self._sink_lock:
            sink, self._sink = self._sink, None
        if sink is not None:
            try:
                sink.close()
            except Exception:
                pass


# --- activation (the faults.py pattern) -----------------------------------

_global: list = [None]


def install(rec: Optional[FlightRecorder]) -> None:
    _global[0] = rec


def uninstall() -> None:
    _global[0] = None


def active() -> Optional[FlightRecorder]:
    return _global[0]


@contextmanager
def activate(rec: FlightRecorder):
    prev = _global[0]
    _global[0] = rec
    try:
        yield rec
    finally:
        _global[0] = prev
