"""In-process SLO engine: declarative objectives, multi-window burn rates.

"Are we inside our latency/staleness objective" becomes a scrape (the
``gatekeeper_slo_*`` gauges) and an endpoint (``/debug/slo``) instead of
a dashboard-side query.  Objectives are declarative dicts (JSON-able —
the ``--slo-config`` file format), three types:

- ``latency`` — a histogram metric + a threshold: the SLI is the
  fraction of observations answered within ``threshold`` seconds
  (computed from the lifetime buckets, so it pairs exactly with the
  exemplar-carrying series on ``/metrics``); ``target`` is the
  objective (e.g. 0.99 = "99% under threshold").
- ``ratio`` — a bad-event counter over a total counter (e.g. shed rate):
  the SLI is the good fraction, ``target`` the floor.
- ``staleness`` — a unix-timestamp gauge (e.g. the audit sweep's last
  end time): the SLI is its age in seconds, ``threshold`` the ceiling.

Burn rate follows the SRE-workbook shape: over a lookback window, the
bad fraction divided by the error budget ``(1 - target)``; 1.0 burns the
budget exactly at the objective's natural rate, 14.4 burns a 30-day
budget in 2 days.  Each *tier* pairs a short and a long window with a
burn threshold — a breach needs BOTH windows hot (the long window
filters blips, the short one ends the alert quickly once recovered).

Each :meth:`SLOEngine.tick` samples the registry into a bounded ring,
evaluates every objective, exports ``gatekeeper_slo_{sli_value,
burn_rate,compliant,breach_count}``, emits an ``slo.breach`` span on the
enter transition, and refreshes the overload controller's pressure when
wired (``OverloadController.set_slo_input`` — the PR 5 brownout ladder
consumes SLO burn as one more pressure signal).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

# the default objective set: names are part of the observability
# registry (tools/observability_registry.md, cross-checked by
# tools/lint_observability.py) — new objectives must land there too
DEFAULT_OBJECTIVES = [
    {
        "name": "admission-latency-p99",
        "type": "latency",
        "metric": "validation_request_duration_seconds",
        "threshold": 0.25,
        "target": 0.99,
        "description": "99% of admission reviews answer within 250ms",
        # the objective's degradation map (--slo-degradation): on a
        # burn breach the engine activates these IN ORDER — cheapest
        # reversible action first, shedding last — and releases them
        # all on the falling edge.  Inert without a DegradationRegistry
        "degradation": ["ns_cache_stale", "extdata_stale",
                        "shed_harder"],
    },
    {
        "name": "mutation-latency-p99",
        "type": "latency",
        "metric": "mutation_request_duration_seconds",
        "threshold": 0.25,
        "target": 0.99,
        "description": "99% of mutate reviews answer within 250ms",
        "degradation": ["ns_cache_stale", "shed_harder"],
    },
    {
        "name": "admission-shed-rate",
        "type": "ratio",
        "bad_metric": "validation_request_count",
        "bad_labels": {"admission_status": "shed"},
        "total_metric": "validation_request_count",
        "target": 0.99,
        "description": "at most 1% of admissions shed under overload",
        # shedding too much: make everything else cheaper before
        # touching the gate itself
        "degradation": ["ns_cache_stale", "extdata_stale"],
    },
    {
        "name": "audit-snapshot-staleness",
        "type": "staleness",
        "gauge": "audit_last_run_end_time",
        "threshold": 600.0,
        "description": "audit verdicts at most 10 minutes stale",
        # a stale audit stops being polite: reclaim the device lane,
        # then stop paying for full resyncs until caught up
        "degradation": ["audit_yield_release", "resync_defer"],
    },
]

# every objective field load_config / SLOObjective accepts — an unknown
# key fails at parse time (the boot-time --slo-config contract), not as
# a mid-run KeyError
_OBJECTIVE_FIELDS = frozenset({
    "name", "type", "metric", "threshold", "target", "description",
    "labels", "bad_metric", "bad_labels", "total_metric",
    "total_labels", "gauge", "degradation", "cluster",
})


class SLOConfigError(ValueError):
    """A ``--slo-config`` document failed validation; the message
    carries the file, line/field, and what was wrong — boot fails fast
    instead of KeyError-ing mid-run."""

# burn-rate alert tiers: (name, short window s, long window s, burn
# threshold) — the SRE-workbook page/ticket pair scaled to a 30d budget
DEFAULT_TIERS = (
    {"name": "page", "short_s": 300.0, "long_s": 3600.0, "burn": 14.4},
    {"name": "ticket", "short_s": 1800.0, "long_s": 21600.0, "burn": 6.0},
)


class SLOObjective:
    """One parsed objective (see module docstring for the dict format)."""

    def __init__(self, spec: dict):
        if not isinstance(spec, dict):
            raise ValueError(f"objective must be a JSON object, got "
                             f"{type(spec).__name__}")
        self.spec = dict(spec)
        if not spec.get("name"):
            raise ValueError("objective is missing the 'name' field")
        self.name = spec["name"]
        unknown = sorted(set(spec) - _OBJECTIVE_FIELDS)
        if unknown:
            raise ValueError(
                f"objective {self.name!r}: unknown field "
                f"{unknown[0]!r} (accepted: {sorted(_OBJECTIVE_FIELDS)})")
        self.type = spec.get("type", "latency")
        if self.type not in ("latency", "ratio", "staleness"):
            raise ValueError(f"objective {self.name!r}: unknown type "
                             f"{self.type!r}")
        self.description = spec.get("description", "")
        try:
            self.target = float(spec.get("target", 0.99))
            self.threshold = float(spec.get("threshold", 0.0))
        except (TypeError, ValueError):
            raise ValueError(f"objective {self.name!r}: 'target'/"
                             f"'threshold' must be numbers") from None
        self.metric = spec.get("metric", "")
        self.labels = spec.get("labels")
        self.bad_metric = spec.get("bad_metric", "")
        self.bad_labels = spec.get("bad_labels")
        self.total_metric = spec.get("total_metric", "")
        self.total_labels = spec.get("total_labels")
        self.gauge = spec.get("gauge", "")
        # ordered degradation map: the named actions this objective may
        # activate on breach (validated against the DegradationRegistry
        # when one is wired; inert otherwise)
        deg = spec.get("degradation", [])
        if not isinstance(deg, (list, tuple)) or \
                any(not isinstance(a, str) or not a for a in deg):
            raise ValueError(f"objective {self.name!r}: 'degradation' "
                             f"must be a list of action names")
        self.degradation = list(deg)
        # fleet scope: a non-empty cluster pins every metric lookup to
        # that cluster's labeled series, and scopes the objective's
        # degradation activations so cluster A never degrades cluster B
        cluster = spec.get("cluster", "")
        if not isinstance(cluster, str):
            raise ValueError(f"objective {self.name!r}: 'cluster' must "
                             f"be a string")
        self.cluster = cluster
        self.budget = max(1e-9, 1.0 - self.target)

    def _scoped(self, base):
        """Metric labels in force: the spec's, plus the cluster axis
        when this objective is fleet-scoped."""
        if not self.cluster:
            return base
        out = dict(base or {})
        out["cluster"] = self.cluster
        return out

    # --- cumulative (bad, total) sampling --------------------------------
    def sample(self, metrics, wall: float):
        """Cumulative (bad, total) counters at this instant — the ring
        entries burn rates difference over.  Staleness objectives return
        their instantaneous age instead (no accumulation)."""
        if self.type == "latency":
            h = metrics.get_histogram(self.metric,
                                      self._scoped(self.labels))
            if h is None:
                return (0.0, 0.0)
            within = 0
            cum = 0
            for i, n in enumerate(h["buckets"]):
                cum += n
                if i < len(h["bounds"]) and \
                        h["bounds"][i] <= self.threshold + 1e-12:
                    within = cum
            return (float(h["count"] - within), float(h["count"]))
        if self.type == "ratio":
            # labels=None sums ACROSS labelsets (shadow divergence is
            # labeled {kind} but the objective wants the sum); an exact
            # labelset filters to one series.  Cluster-scoped
            # objectives sum across the labelsets carrying their
            # cluster (plus any configured label pairs) — one
            # cluster's series out of the fleet's shared registry
            if self.cluster:
                bad = metrics.counter_total(
                    self.bad_metric, match=self._scoped(self.bad_labels))
                total = metrics.counter_total(
                    self.total_metric,
                    match=self._scoped(self.total_labels))
                return (float(bad), float(total))
            if self.bad_labels is None:
                bad = metrics.counter_total(self.bad_metric)
            else:
                bad = metrics.get_counter(self.bad_metric,
                                          self.bad_labels)
            if self.total_labels is None:
                total = metrics.counter_total(self.total_metric)
            else:
                total = metrics.get_counter(self.total_metric,
                                            self.total_labels)
            return (float(bad), float(total))
        # staleness: age of the gauge timestamp (gauge unset = age 0 —
        # nothing has run yet, nothing is stale yet)
        ts = metrics.get_gauge(self.gauge, self._scoped(self.labels))
        age = max(0.0, wall - float(ts)) if ts else 0.0
        return (age, -1.0)  # total=-1 marks "instantaneous value"


class SLOEngine:
    """Evaluates objectives against the metrics registry on ``tick()``.

    ``clock`` is the monotonic ring clock and ``wall`` the exemplar /
    staleness clock — injectable so tests replay exact trajectories."""

    def __init__(self, metrics, objectives: Optional[Sequence] = None,
                 tiers: Optional[Sequence] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 ring_capacity: int = 4096,
                 brownout=None,
                 degradations=None,
                 escalate_hold_s: float = 30.0):
        self.metrics = metrics
        self.objectives = [
            o if isinstance(o, SLOObjective) else SLOObjective(o)
            for o in (objectives if objectives is not None
                      else DEFAULT_OBJECTIVES)]
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.tiers = [dict(t) for t in (tiers or DEFAULT_TIERS)]
        self._clock = clock
        self._wall = wall
        # ring of (t, {objective: (bad_cum, total_cum)}) samples
        self._ring: deque = deque(maxlen=ring_capacity)
        self._breached: dict = {}  # objective -> bool (edge detection)
        self._last_eval: dict = {}
        self._lock = threading.Lock()
        # optional OverloadController: tick() refreshes its pressure so
        # SLO burn feeds the brownout ladder (set_slo_input must point
        # back at self.pressure for the signal to be consumed)
        self.brownout = brownout
        # optional DegradationRegistry (resilience/overload.py): tick()
        # then drives each breaching objective's degradation MAP —
        # activate the next mapped action after escalate_hold_s of
        # sustained breach, release them all on the falling edge.  None
        # keeps the scalar --slo-brownout path the only feedback loop
        # (bit-identical to the pre-map engine)
        self.degradations = degradations
        self.escalate_hold_s = float(escalate_hold_s)
        if degradations is not None:
            for o in self.objectives:
                degradations.validate(
                    o.degradation, where=f"objective {o.name!r}")
        self._deg_level: dict = {}  # objective -> active action count
        self._deg_at: dict = {}  # objective -> clock of last transition
        # every activation/release edge in decision order — identical
        # (config, clock, metric sequence) replays it exactly (pinned)
        self.degradation_trajectory: deque = deque(maxlen=4096)
        # (objective_filter, fn) called on each breach RISING EDGE —
        # "" matches every objective; see on_breach()
        self._breach_hooks: list = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def window_clock(self) -> Callable[[], float]:
        """The monotonic clock the burn windows run on — consumers that
        want to age state on the SLO timebase (e.g. the QoS displacement
        ledger under ``--qos-ledger-decay slo-window``) read it here so
        an injected test clock drives them too."""
        return self._clock

    def shortest_window_s(self) -> float:
        """The tightest burn-tier short window — the natural half-life
        for window-driven decay consumers."""
        return min((float(t["short_s"]) for t in self.tiers),
                   default=300.0)

    # --- loop ------------------------------------------------------------
    def start(self, interval_s: float = 10.0) -> "SLOEngine":
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:
                    pass  # the SLO engine must never take the server down
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slo-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # --- breach hooks -----------------------------------------------------
    def on_breach(self, fn, objective: str = "") -> None:
        """Register ``fn(objective_name, eval_dict)`` to fire on a
        breach RISING EDGE only (not on every breached tick — re-arming
        requires the objective to recover first).  ``objective`` filters
        to one objective name; ``""`` fires for all.  Hook exceptions
        are swallowed: the engine must never take the server down."""
        self._breach_hooks.append((objective, fn))

    # --- evaluation -------------------------------------------------------
    def tick(self) -> dict:
        """Sample + evaluate + export; returns the ``/debug/slo``
        payload for this instant."""
        from gatekeeper_tpu.metrics import registry as M
        from gatekeeper_tpu.observability import tracing

        now = self._clock()
        wall = self._wall()
        sample = {o.name: o.sample(self.metrics, wall)
                  for o in self.objectives}
        with self._lock:
            self._ring.append((now, sample))
            evals = [self._evaluate_locked(o, now, sample[o.name])
                     for o in self.objectives]
        for o, ev in zip(self.objectives, evals):
            o_name = ev["name"]
            self.metrics.set_gauge(M.SLO_SLI, ev["sli"],
                                   {"objective": o_name})
            self.metrics.set_gauge(M.SLO_COMPLIANT,
                                   1.0 if ev["compliant"] else 0.0,
                                   {"objective": o_name})
            for wname, rate in ev["burn"].items():
                self.metrics.set_gauge(M.SLO_BURN_RATE, rate,
                                       {"objective": o_name,
                                        "window": wname})
            was = self._breached.get(o_name, False)
            if ev["breach"] and not was:
                self.metrics.inc_counter(M.SLO_BREACHES,
                                         {"objective": o_name})
                # breach transitions land in the trace timeline too: a
                # root span (visible without any ambient request) plus an
                # event on whatever span is ambient
                with tracing.span("slo.breach", objective=o_name,
                                  sli=ev["sli"], tier=ev["breach_tier"]):
                    pass
                tracing.add_event("slo_breach", objective=o_name,
                                  sli=ev["sli"])
                try:
                    from gatekeeper_tpu.utils.logging import log_event

                    log_event("warning", "SLO burn-rate breach",
                              event_type="slo_breach", objective=o_name,
                              sli=ev["sli"], tier=ev["breach_tier"])
                except Exception:
                    pass
                for want, fn in list(self._breach_hooks):
                    if want and want != o_name:
                        continue
                    try:
                        fn(o_name, ev)
                    except Exception:
                        pass
            self._breached[o_name] = ev["breach"]
            self._degrade_step(o, ev, now)
        payload = {
            "generated_at": wall,
            "pressure": self._pressure_from(evals),
            "tiers": self.tiers,
            "objectives": evals,
        }
        with self._lock:
            self._last_eval = payload
        if self.brownout is not None:
            try:
                self.brownout.refresh_pressure()
            except Exception:
                pass
        return payload

    def _window_burn(self, objective: SLOObjective, now: float,
                     window_s: float, cur) -> float:
        """Burn rate over the trailing window: Δbad/Δtotal scaled by the
        error budget.  Staleness objectives burn as age/threshold."""
        bad, total = cur
        if total < 0:  # instantaneous (staleness)
            return (bad / objective.threshold) if objective.threshold \
                else 0.0
        base = None
        older = None  # newest sample just OUTSIDE the window
        for t, sample in self._ring:
            if now - t <= window_s:
                base = sample.get(objective.name)
                break
            older = sample.get(objective.name)
        if base is None:
            # tick gap wider than the window: difference against the
            # newest pre-window sample instead of the whole lifetime
            base = older if older is not None else (0.0, 0.0)
        d_bad = max(0.0, bad - base[0])
        d_total = max(0.0, total - base[1])
        if d_total <= 0:
            return 0.0
        return (d_bad / d_total) / objective.budget

    def _evaluate_locked(self, o: SLOObjective, now: float, cur) -> dict:
        bad, total = cur
        if total < 0:
            sli = bad  # staleness: the age itself
            compliant = sli <= o.threshold
        elif total > 0:
            sli = 1.0 - bad / total  # good fraction, lifetime
            compliant = sli >= o.target
        else:
            sli = 1.0
            compliant = True
        burns: dict = {}
        breach = False
        breach_tier = ""
        for tier in self.tiers:
            bs = self._window_burn(o, now, tier["short_s"], cur)
            bl = self._window_burn(o, now, tier["long_s"], cur)
            burns[f"{int(tier['short_s'])}s"] = round(bs, 4)
            burns[f"{int(tier['long_s'])}s"] = round(bl, 4)
            if bs >= tier["burn"] and bl >= tier["burn"] and not breach:
                breach = True
                breach_tier = tier["name"]
        if total < 0 and not compliant:
            # staleness has no budget to burn down: out of objective IS
            # the breach (age past the ceiling pages immediately)
            breach = True
            breach_tier = breach_tier or "page"
        return {
            "name": o.name,
            "type": o.type,
            "description": o.description,
            "cluster": o.cluster,
            "target": o.target,
            "threshold": o.threshold,
            "sli": round(sli, 6),
            "compliant": compliant,
            "burn": burns,
            "breach": breach,
            "breach_tier": breach_tier,
        }

    # --- degradation maps -------------------------------------------------
    def _degrade_step(self, o: SLOObjective, ev: dict,
                      now: float) -> None:
        """Drive one objective's degradation map off its breach state:
        rising edge activates the first mapped action; a breach held
        past ``escalate_hold_s`` since the last transition escalates to
        the next; the falling edge releases every held action in
        reverse order.  Pure function of (map, clock, breach sequence)
        — an injected clock replays the exact trajectory."""
        reg = self.degradations
        ev["degradation"] = list(o.degradation)
        if reg is None or not o.degradation:
            ev["degradation_active"] = []
            return
        level = self._deg_level.get(o.name, 0)
        if ev["breach"]:
            if level == 0:
                self._deg_transition(o, o.degradation[0], ev, now, True)
                level = 1
            elif level < len(o.degradation) and \
                    now - self._deg_at.get(o.name, now) >= \
                    self.escalate_hold_s:
                self._deg_transition(o, o.degradation[level], ev, now,
                                     True)
                level += 1
            else:
                ev["degradation_active"] = list(o.degradation[:level])
                return
            self._deg_level[o.name] = level
            self._deg_at[o.name] = now
        elif level > 0:
            # falling edge: revoke deepest-first — the map unwinds the
            # way it wound up
            for action in reversed(o.degradation[:level]):
                self._deg_transition(o, action, ev, now, False)
            level = 0
            self._deg_level[o.name] = 0
            self._deg_at[o.name] = now
        ev["degradation_active"] = list(o.degradation[:level])

    def _deg_transition(self, o: SLOObjective, action: str, ev: dict,
                        now: float, activate: bool) -> None:
        from gatekeeper_tpu.observability import tracing

        if activate:
            self.degradations.activate(action, objective=o.name,
                                       cluster=o.cluster)
        else:
            self.degradations.release(action, objective=o.name,
                                      cluster=o.cluster)
        event = "activate" if activate else "release"
        self.degradation_trajectory.append({
            "t": round(now, 6), "objective": o.name, "action": action,
            "cluster": o.cluster, "event": event,
        })
        # the transition lands in the trace timeline (a root span,
        # visible without any ambient request) and the event stream
        with tracing.span("slo.degrade", objective=o.name,
                          action=action, cluster=o.cluster,
                          event=event, sli=ev["sli"]):
            pass
        tracing.add_event("slo_degrade", objective=o.name,
                          action=action, event=event)
        try:
            from gatekeeper_tpu.utils.logging import log_event

            log_event("warning" if activate else "info",
                      f"SLO degradation {event}",
                      event_type="slo_degrade", objective=o.name,
                      action=action, cluster=o.cluster, sli=ev["sli"])
        except Exception:
            pass

    def _pressure_from(self, evals) -> float:
        """0..1 brownout input: the hottest objective's fastest-tier burn
        relative to that tier's threshold, capped at 1 — at 1.0 the
        ladder sees SLO burn as a full queue would look."""
        if not self.tiers:
            return 0.0
        tier = self.tiers[0]
        wname = f"{int(tier['short_s'])}s"
        p = 0.0
        for ev in evals:
            p = max(p, ev["burn"].get(wname, 0.0) / tier["burn"])
        return min(1.0, p)

    # --- consumers --------------------------------------------------------
    def pressure(self) -> float:
        """The brownout-ladder input (see ``_pressure_from``); reads the
        last tick's evaluation — wire via
        ``OverloadController.set_slo_input(engine.pressure)``."""
        with self._lock:
            return float(self._last_eval.get("pressure", 0.0))

    def snapshot(self, cluster: Optional[str] = None) -> dict:
        """The ``/debug/slo`` payload (last tick; {} before the first).
        ``cluster`` filters to one cluster's fleet-scoped objectives
        plus the global (unscoped) ones — the ``?cluster=`` view."""
        with self._lock:
            out = dict(self._last_eval)
        if cluster is not None and out:
            out = dict(out)
            out["cluster"] = cluster
            out["objectives"] = [
                ev for ev in out.get("objectives", [])
                if ev.get("cluster", "") in ("", cluster)]
        return out

    def degraded(self) -> dict:
        """objective -> [active actions], for every objective holding
        at least one (the triage cross-link source)."""
        out: dict = {}
        for o in self.objectives:
            lvl = self._deg_level.get(o.name, 0)
            if lvl:
                out[o.name] = list(o.degradation[:lvl])
        return out


def per_cluster_objectives(cluster_ids: Sequence[str],
                           base: Optional[Sequence] = None) -> list:
    """Fleet-scoped objective set: every base objective cloned once per
    cluster as ``name@cluster`` with the ``cluster`` axis set, so SLIs
    read that cluster's labeled series and degradation actions scope to
    it.  ``base`` defaults to :data:`DEFAULT_OBJECTIVES`."""
    out: list = []
    for cid in cluster_ids:
        for spec in (base if base is not None else DEFAULT_OBJECTIVES):
            spec = dict(spec.spec if isinstance(spec, SLOObjective)
                        else spec)
            spec["name"] = f"{spec['name']}@{cid}"
            spec["cluster"] = cid
            out.append(SLOObjective(spec))
    return out


def load_config(path: str, degradations=None) -> dict:
    """{"objectives": [SLOObjective...], "tiers": [...] or None,
    "actions": [names registered]}.

    Fails fast with :class:`SLOConfigError` naming the line (malformed
    JSON) or the objective index + field (bad spec); degradation-map
    action names are validated against ``degradations`` (a
    DegradationRegistry) when given.

    A top-level ``"actions"`` list registers CUSTOM degradation actions
    into ``degradations`` BEFORE the objective maps validate, so an
    objective may name them: each entry is ``{"name": ...,
    "description": ...}`` (description optional, unknown fields
    rejected).  Config-registered actions have no built-in consumer —
    they surface through ``degradation_active`` polls and the registry's
    activate/release hooks, which is exactly what operator-side
    consumers (and the built-in ``device_residency_evict`` poll in
    snapshot/device_residency.py) key on."""
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise SLOConfigError(
            f"{path}:{e.lineno}:{e.colno}: malformed JSON: "
            f"{e.msg}") from None
    specs = doc if isinstance(doc, list) else doc.get("objectives", [])
    tiers = None if isinstance(doc, list) else (doc.get("tiers") or None)
    actions = [] if isinstance(doc, list) else (doc.get("actions") or [])
    if not isinstance(specs, list):
        raise SLOConfigError(f"{path}: 'objectives' must be a list")
    if not isinstance(actions, list):
        raise SLOConfigError(f"{path}: 'actions' must be a list")
    registered: list = []
    for i, a in enumerate(actions):
        if not isinstance(a, dict):
            raise SLOConfigError(
                f"{path}: actions[{i}]: must be an object")
        name = a.get("name")
        if not name or not isinstance(name, str):
            raise SLOConfigError(
                f"{path}: actions[{i}]: missing or non-string 'name'")
        desc = a.get("description", "")
        if not isinstance(desc, str):
            raise SLOConfigError(
                f"{path}: actions[{i}]: 'description' must be a string")
        unknown = set(a) - {"name", "description"}
        if unknown:
            raise SLOConfigError(
                f"{path}: actions[{i}]: unknown field(s) "
                f"{sorted(unknown)}")
        if degradations is not None:
            degradations.register(name, desc)
        registered.append(name)
    objectives: list = []
    for i, spec in enumerate(specs):
        try:
            objectives.append(SLOObjective(spec))
        except ValueError as e:
            raise SLOConfigError(
                f"{path}: objectives[{i}]: {e}") from None
    if tiers is not None:
        if not isinstance(tiers, list):
            raise SLOConfigError(f"{path}: 'tiers' must be a list")
        for i, t in enumerate(tiers):
            if not isinstance(t, dict) or not t.get("name"):
                raise SLOConfigError(
                    f"{path}: tiers[{i}]: must be an object with a "
                    f"'name'")
            for field in ("short_s", "long_s", "burn"):
                try:
                    float(t[field])
                except (KeyError, TypeError, ValueError):
                    raise SLOConfigError(
                        f"{path}: tiers[{i}]: missing or non-numeric "
                        f"field {field!r}") from None
    if degradations is not None:
        for i, o in enumerate(objectives):
            try:
                degradations.validate(
                    o.degradation, where=f"objective {o.name!r}")
            except ValueError as e:
                raise SLOConfigError(
                    f"{path}: objectives[{i}]: {e}") from None
    return {"objectives": objectives, "tiers": tiers,
            "actions": registered}
