"""Observability layer: end-to-end span tracing + device-timeline export.

``tracing`` is the dependency-free span tracer (trace/span IDs, parent
links, events, contextvar propagation, W3C traceparent interop, tail-
sampled ring buffer); ``export`` renders kept traces as Chrome
trace-event JSON (Perfetto-loadable) and self-time summaries.  The
tracer is the one timeline that connects the webhook HTTP path, the
batcher lane, device dispatch, and every audit-sweep pipeline stage —
with the resilience layer's retries, breaker transitions, deadline
misses and injected faults attached as span events.
"""

from gatekeeper_tpu.observability.export import (  # noqa: F401
    chrome_trace,
    format_span_summary,
    top_spans_by_self_time,
    write_chrome_trace,
)
from gatekeeper_tpu.observability.tracing import (  # noqa: F401
    NOOP_SPAN,
    Span,
    SpanContext,
    Tracer,
    activate,
    active_tracer,
    add_event,
    current_span,
    enabled,
    format_traceparent,
    install,
    parse_traceparent,
    set_attribute,
    span,
    uninstall,
    use_span,
)
