"""Observability layer: spans, cost attribution, SLOs, flight recorder.

``tracing`` is the dependency-free span tracer (trace/span IDs, parent
links, events, contextvar propagation, W3C traceparent interop, tail-
sampled ring buffer); ``export`` renders kept traces as Chrome
trace-event JSON (Perfetto-loadable) and self-time summaries.  The
tracer is the one timeline that connects the webhook HTTP path, the
batcher lane, device dispatch, and every audit-sweep pipeline stage —
with the resilience layer's retries, breaker transitions, deadline
misses and injected faults attached as span events.

On top of the timeline, three production answers (README
"Observability"):

- ``costattr`` — per-template cost attribution: shared device passes
  apportion their wall time across the constraint grid by row
  occupancy ("which policy is expensive" at ``/debug/cost``);
- ``slo`` — declarative objectives with multi-window burn rates
  ("are we inside our objective" at ``/debug/slo``, breach span
  events, a pressure input for the overload brownout ladder);
- ``flightrec`` — the admission flight recorder: a bounded ring of
  every admission/mutation/shed decision ("why was THIS request shed"
  at ``/debug/decisions?uid=``), with an optional JSONL sink.

Metrics cross-link the three: histogram buckets carry trace-id
exemplars, decisions carry trace ids, and attribution shares carry the
enforcement point — so a slow P99 bucket walks to its span, its cost
cell, and its decision record.
"""

from gatekeeper_tpu.observability import (  # noqa: F401
    costattr,
    flightrec,
    slo,
)
from gatekeeper_tpu.observability.costattr import (  # noqa: F401
    CostAttribution,
)
from gatekeeper_tpu.observability.flightrec import (  # noqa: F401
    FlightRecorder,
)
from gatekeeper_tpu.observability.slo import (  # noqa: F401
    SLOEngine,
    SLOObjective,
)
from gatekeeper_tpu.observability.export import (  # noqa: F401
    chrome_trace,
    format_span_summary,
    top_spans_by_self_time,
    write_chrome_trace,
)
from gatekeeper_tpu.observability.tracing import (  # noqa: F401
    NOOP_SPAN,
    Span,
    SpanContext,
    Tracer,
    activate,
    active_tracer,
    add_event,
    current_span,
    enabled,
    format_traceparent,
    install,
    parse_traceparent,
    set_attribute,
    span,
    uninstall,
    use_span,
)
