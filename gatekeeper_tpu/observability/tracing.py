"""Dependency-free span tracer: one timeline from AdmissionReview to
XLA dispatch.

The reference Gatekeeper wires OTel tracing through ``pkg/metrics`` so a
single admission request (or one audit sweep chunk) can be followed
across layers; here the same Dapper-style request-scoped span model is
rebuilt on the stdlib only, reusing the contextvar-propagation pattern
the resilience layer's :class:`Deadline` budget already uses:

- :class:`Span` — trace/span IDs, a parent link, wall-clock bounds,
  attributes, and point-in-time *events* (retries, breaker transitions,
  deadline misses and injected chaos faults all land here, so a
  ``--chaos`` run shows exactly where the fault hit).
- :class:`Tracer` — creates spans (IDs come from a seeded RNG, so a
  test seed replays the exact ID sequence), buffers the spans of each
  in-flight trace, and *tail-samples* finished traces into a bounded
  ring buffer: traces slower than ``slow_threshold_s`` are always kept,
  the rest keep with probability ``sample_rate``.  ``sample_rate=0``
  with no threshold is the "empty sampler" — the tracer runs the full
  span machinery but retains nothing, which the differential tests use
  to prove tracing is zero-cost to verdicts.
- activation mirrors ``resilience/faults.py``: :func:`install` is the
  process-global switch (the ``--trace`` CLI flag — worker threads
  spawned before any contextvar was set still see it), and
  :func:`activate` is the scoped variant for tests.

With no tracer installed every entry point (:func:`span`,
:func:`add_event`, :func:`current_span`) is one contextvar read plus one
global read — nanoseconds, no locks, no behavior change.  Cross-thread
propagation (batcher lane, pipeline stage workers, the webhook deadline
helper thread) is explicit: capture :func:`current_span` on the
submitting thread, re-enter it with :func:`use_span` (or pass it as
``parent=``) on the worker.

W3C trace-context interop: :func:`parse_traceparent` ingests an incoming
``traceparent`` header as a remote parent (the webhook HTTP path), and
:func:`format_traceparent` emits the current span's context on outbound
calls (external-data provider sends, apiserver requests).
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Optional

TRACEPARENT_HEADER = "traceparent"

_UNSET = object()  # span(parent=...) sentinel: "use the ambient span"


class SpanContext:
    """A remote span reference (an ingested ``traceparent``): enough to
    parent a local span into the caller's trace without a local Span."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


class Span:
    """One timed operation.  Mutate only from the thread(s) that own the
    operation; ``add_event``/``set_attribute`` are lock-free appends."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ts",
                 "duration_s", "attributes", "events", "status", "error",
                 "thread_id", "thread_name", "is_root", "_t0", "_tracer")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], is_root: bool, tracer: "Tracer"):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.is_root = is_root
        self.start_ts = tracer._wall()
        self._t0 = tracer._clock()
        self.duration_s = 0.0
        self.attributes: dict = {}
        self.events: list = []
        self.status = "ok"
        self.error = ""
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self._tracer = tracer

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        self.events.append({"ts": self._tracer._wall(), "name": name,
                            "attrs": attrs})

    def set_status(self, status: str, error: str = "") -> None:
        self.status = status
        self.error = error

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ts": self.start_ts,
            "duration_s": self.duration_s,
            "thread_id": self.thread_id,
            "thread_name": self.thread_name,
            "attributes": dict(self.attributes),
            "events": list(self.events),
            "status": self.status,
            **({"error": self.error} if self.error else {}),
        }


class _NoopSpan:
    """Returned by :func:`span` when no tracer is installed: every method
    is a no-op, so call sites never branch on tracing being enabled."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    name = ""

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attrs: Any) -> None:
        pass

    def set_status(self, status: str, error: str = "") -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Span factory + per-trace buffer + tail-sampled ring buffer.

    ``seed`` drives BOTH the ID generator and the sampling RNG, so a
    seeded run replays the same trace/span IDs and the same keep/drop
    decisions (the chaos-differential discipline applied to tracing).
    ``seed=None`` draws from OS entropy (production default)."""

    def __init__(self, seed: Optional[int] = 0,
                 ring_capacity: int = 256,
                 slow_threshold_s: Optional[float] = None,
                 sample_rate: float = 1.0,
                 max_spans_per_trace: int = 4096,
                 clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time,
                 metrics=None):
        self._rng = random.Random(seed)
        self._clock = clock
        self._wall = wall
        self.slow_threshold_s = slow_threshold_s
        self.sample_rate = float(sample_rate)
        self.max_spans_per_trace = max_spans_per_trace
        self.metrics = metrics
        self._lock = threading.Lock()
        # trace_id -> list of finished span dicts, awaiting the root's end
        self._pending: dict = {}
        self._ring: deque = deque(maxlen=max(1, ring_capacity))
        self.kept = 0
        self.sampled_out = 0
        self.span_count = 0  # spans STARTED (includes sampled-out traces)

    # --- IDs --------------------------------------------------------------
    def _gen_trace_id(self) -> str:
        return f"{self._rng.getrandbits(128):032x}"

    def _gen_span_id(self) -> str:
        return f"{self._rng.getrandbits(64):016x}"

    # --- span lifecycle ---------------------------------------------------
    def start_span(self, name: str, parent=None,
                   attributes: Optional[dict] = None) -> Span:
        """``parent`` may be a local :class:`Span`, a remote
        :class:`SpanContext` (ingested traceparent), or None (new trace).
        A span with no *local* parent is its trace's local root — its end
        finalizes the trace through the tail sampler."""
        with self._lock:
            if parent is None:
                trace_id = self._gen_trace_id()
                parent_id = None
            else:
                trace_id = parent.trace_id
                parent_id = parent.span_id
            span_id = self._gen_span_id()
            self.span_count += 1
        is_root = parent is None or isinstance(parent, SpanContext)
        s = Span(name, trace_id, span_id, parent_id, is_root, self)
        if attributes:
            s.attributes.update(attributes)
        return s

    def end_span(self, s: Span) -> None:
        s.duration_s = self._clock() - s._t0
        with self._lock:
            buf = self._pending.setdefault(s.trace_id, [])
            if len(buf) < self.max_spans_per_trace:
                buf.append(s.to_dict())
            if s.is_root:
                spans = self._pending.pop(s.trace_id, [])
                self._finalize(s, spans)
            elif len(self._pending) > 4096:
                # straggler bound: a span ending after its root finalized
                # (a batch-thread tail racing the request thread) re-seeds
                # _pending with an entry no root will ever drain — prune
                # oldest-first so a long-running server can't grow it
                self._pending.pop(next(iter(self._pending)))

    def _finalize(self, root: Span, spans: list) -> None:
        """Tail-sampling decision at trace end (call under self._lock):
        slow traces always keep; the rest keep at ``sample_rate``."""
        if self.slow_threshold_s is not None \
                and root.duration_s >= self.slow_threshold_s:
            keep = True  # slow traces always keep (the tail matters most)
        elif self.sample_rate >= 1.0:
            keep = True
        elif self.sample_rate <= 0.0:
            keep = False
        else:
            keep = self._rng.random() < self.sample_rate
        if not keep:
            self.sampled_out += 1
            self._count("trace_traces_sampled_out_count")
            return
        self.kept += 1
        self._count("trace_traces_kept_count")
        self._ring.append({
            "trace_id": root.trace_id,
            "root": root.name,
            "start_ts": root.start_ts,
            "duration_s": root.duration_s,
            "n_spans": len(spans),
            "spans": spans,
        })

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            try:
                self.metrics.inc_counter(name)
            except Exception:
                pass  # tracing must never add a failure mode of its own

    # --- introspection ----------------------------------------------------
    def traces(self) -> list:
        """Snapshot of the kept-trace ring buffer (oldest first)."""
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> dict:
        """The ``/debug/traces`` payload."""
        with self._lock:
            return {
                "kept": self.kept,
                "sampled_out": self.sampled_out,
                "spans_started": self.span_count,
                "ring_capacity": self._ring.maxlen,
                "slow_threshold_s": self.slow_threshold_s,
                "sample_rate": self.sample_rate,
                "traces": list(self._ring),
            }


# --- activation (the faults.py pattern) ----------------------------------

_ctx_tracer: contextvars.ContextVar = contextvars.ContextVar(
    "gatekeeper_tracer", default=None)
_global_tracer: list = [None]  # process-scoped (--trace; worker threads)
_ctx_span: contextvars.ContextVar = contextvars.ContextVar(
    "gatekeeper_span", default=None)


def install(tracer: Optional[Tracer]) -> None:
    """Process-global activation (the ``--trace`` flag): every thread
    sees the tracer, including workers spawned before the call."""
    _global_tracer[0] = tracer


def uninstall() -> None:
    _global_tracer[0] = None


def active_tracer() -> Optional[Tracer]:
    t = _ctx_tracer.get()
    if t is None:
        t = _global_tracer[0]
    return t


@contextmanager
def activate(tracer: Tracer, process: bool = True):
    """Scoped activation for tests: contextvar (same thread) and — by
    default — the process global, so spans on worker threads (batcher,
    pipeline stages) reach the same tracer.  Restores both on exit."""
    token = _ctx_tracer.set(tracer)
    prev = _global_tracer[0]
    if process:
        _global_tracer[0] = tracer
    try:
        yield tracer
    finally:
        _ctx_tracer.reset(token)
        if process:
            _global_tracer[0] = prev


# --- the hot-path entry points -------------------------------------------

def current_span() -> Optional[Span]:
    return _ctx_span.get()


def add_event(name: str, **attrs: Any) -> None:
    """Attach an event to the ambient span (no-op when none): the seam
    the resilience layer uses — retries, breaker transitions, deadline
    misses and injected faults become span events through this call."""
    s = _ctx_span.get()
    if s is not None:
        s.add_event(name, **attrs)


def set_attribute(key: str, value: Any) -> None:
    s = _ctx_span.get()
    if s is not None:
        s.set_attribute(key, value)


@contextmanager
def span(name: str, parent=_UNSET, **attrs: Any):
    """Open a span as a context manager.  With no tracer installed this
    yields the shared no-op span (one contextvar read + one global read).
    ``parent`` defaults to the ambient span; pass an explicit Span /
    SpanContext for cross-thread or remote parenting, or None to force a
    new root."""
    tracer = _ctx_tracer.get()
    if tracer is None:
        tracer = _global_tracer[0]
        if tracer is None:
            yield NOOP_SPAN
            return
    p = _ctx_span.get() if parent is _UNSET else parent
    s = tracer.start_span(name, parent=p, attributes=attrs)
    token = _ctx_span.set(s)
    try:
        yield s
    except BaseException as e:  # noqa: BLE001 — annotate and re-raise
        s.set_status("error", f"{type(e).__name__}: {e}")
        raise
    finally:
        _ctx_span.reset(token)
        tracer.end_span(s)


@contextmanager
def use_span(s: Optional[Span]):
    """Re-enter an existing span on another thread (the cross-thread
    propagation seam: batcher entries, pipeline workers, the webhook's
    deadline helper thread).  The span is NOT ended on exit — its owner
    ends it."""
    token = _ctx_span.set(s)
    try:
        yield s
    finally:
        _ctx_span.reset(token)


def enabled() -> bool:
    return _ctx_tracer.get() is not None or _global_tracer[0] is not None


# --- W3C trace-context ----------------------------------------------------

def format_traceparent(s: Optional[Span] = None) -> Optional[str]:
    """``00-<trace_id>-<span_id>-01`` for the given (default: ambient)
    span; None when there is nothing to propagate."""
    if s is None:
        s = _ctx_span.get()
    if s is None or not getattr(s, "trace_id", ""):
        return None
    return f"00-{s.trace_id}-{s.span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Validate + parse an incoming ``traceparent`` header into a remote
    :class:`SpanContext`; malformed headers return None (the request
    simply starts a fresh trace — never an error)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id.lower(), span_id.lower())
