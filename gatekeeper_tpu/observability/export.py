"""Trace export: Chrome trace-event JSON (Perfetto-loadable) and the
top-spans-by-self-time summary.

The Chrome trace-event format is the lowest-common-denominator timeline
interchange: ``chrome://tracing`` and https://ui.perfetto.dev both load
``{"traceEvents": [...]}`` with complete events (``ph: "X"``, micro-
second ``ts``/``dur``) directly.  Spans become complete events laid out
per thread; span *events* (retries, breaker transitions, injected
faults, deadline misses) become instant events (``ph: "i"``) so a chaos
run's faults are visible as markers on the exact span they hit.
"""

from __future__ import annotations

import json
import os
from typing import Optional


def chrome_trace_events(traces: list, pid: Optional[int] = None) -> list:
    """Flatten kept traces into a Chrome trace-event list."""
    pid = os.getpid() if pid is None else pid
    events: list = []
    thread_names: dict = {}
    for trace in traces:
        for sp in trace.get("spans", []):
            tid = sp.get("thread_id", 0)
            tname = sp.get("thread_name", "")
            if tname and tid not in thread_names:
                thread_names[tid] = tname
            args = dict(sp.get("attributes") or {})
            args["trace_id"] = sp.get("trace_id", "")
            args["span_id"] = sp.get("span_id", "")
            if sp.get("parent_id"):
                args["parent_id"] = sp["parent_id"]
            if sp.get("status") != "ok":
                args["status"] = sp.get("status")
                if sp.get("error"):
                    args["error"] = sp["error"]
            events.append({
                "ph": "X",
                "name": sp.get("name", ""),
                "cat": (sp.get("name", "") or "span").split(".")[0],
                "ts": round(sp.get("start_ts", 0.0) * 1e6, 3),
                "dur": round(max(0.0, sp.get("duration_s", 0.0)) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            })
            for ev in sp.get("events", []):
                events.append({
                    "ph": "i",
                    "s": "t",  # thread-scoped instant marker
                    "name": ev.get("name", ""),
                    "cat": "event",
                    "ts": round(ev.get("ts", 0.0) * 1e6, 3),
                    "pid": pid,
                    "tid": tid,
                    "args": dict(ev.get("attrs") or {}),
                })
    for tid, tname in sorted(thread_names.items()):
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": tname},
        })
    return events


def chrome_trace(traces: list) -> dict:
    return {
        "traceEvents": chrome_trace_events(traces),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "gatekeeper-tpu span tracer"},
    }


def write_chrome_trace(path: str, tracer) -> int:
    """Export a tracer's kept traces to ``path``; returns the number of
    trace-event records written."""
    doc = chrome_trace(tracer.traces())
    with open(path, "w") as f:
        json.dump(doc, f)
        f.write("\n")
    return len(doc["traceEvents"])


# --- self-time summary ----------------------------------------------------

def self_times(traces: list) -> dict:
    """Aggregate per span NAME: ``{name: (total_self_s, count)}``.
    Self-time is a span's duration minus its direct children's durations
    (clamped at 0 — children on other threads can overlap the parent),
    the standard profile ranking for 'where did the wall actually go'."""
    agg: dict = {}
    for trace in traces:
        spans = trace.get("spans", [])
        child_sum: dict = {}
        for sp in spans:
            pid = sp.get("parent_id")
            if pid:
                child_sum[pid] = (child_sum.get(pid, 0.0)
                                  + sp.get("duration_s", 0.0))
        for sp in spans:
            self_s = max(0.0, sp.get("duration_s", 0.0)
                         - child_sum.get(sp.get("span_id"), 0.0))
            name = sp.get("name", "")
            tot, cnt = agg.get(name, (0.0, 0))
            agg[name] = (tot + self_s, cnt + 1)
    return agg


def top_spans_by_self_time(traces: list, top: int = 3) -> list:
    """[(name, total_self_s, count)] ranked by total self-time."""
    agg = self_times(traces)
    ranked = sorted(agg.items(), key=lambda kv: kv[1][0], reverse=True)
    return [(name, tot, cnt) for name, (tot, cnt) in ranked[:top]]


def format_span_summary(traces: list, top: int = 3) -> str:
    """One-line summary (``gator bench`` prints this after each engine
    run): the top-N spans by self-time."""
    ranked = top_spans_by_self_time(traces, top=top)
    if not ranked:
        return "spans: (no traces kept)"
    parts = [f"{name} {tot:.3f}s/{cnt}x" for name, tot, cnt in ranked]
    return "spans (top self-time): " + ", ".join(parts)
