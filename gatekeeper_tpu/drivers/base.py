"""The Driver interface — the L1→L0 seam.

Reference: the ``drivers.Driver`` interface mirrored exactly by
pkg/drivers/k8scel/driver.go:70-263 (Name / AddTemplate / RemoveTemplate /
AddConstraint / RemoveConstraint / AddData / RemoveData / Query / Dump /
GetDescriptionForStat).  Everything above this seam treats policy evaluation
as opaque; the TPU engine registers here beside the interpreter engine just as
k8scel registers beside rego in the reference (main.go:465-485).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Protocol, Sequence

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.client.types import QueryResponse
from gatekeeper_tpu.target.review import GkReview


@dataclass
class ReviewCfg:
    """Per-review options (reference: reviews.ReviewCfg, k8scel/driver.go:163)."""

    enforcement_point: str = ""
    tracing: bool = False
    stats: bool = False


class Driver(Protocol):
    def name(self) -> str: ...

    def add_template(self, template: ConstraintTemplate) -> None: ...

    def remove_template(self, template_kind: str) -> None: ...

    def add_constraint(self, constraint: Constraint) -> None: ...

    def remove_constraint(self, constraint: Constraint) -> None: ...

    def add_data(self, target: str, path: Sequence[str], data: Any) -> None: ...

    def remove_data(self, target: str, path: Sequence[str]) -> None: ...

    def query(
        self,
        target: str,
        constraints: Sequence[Constraint],
        review: GkReview,
        cfg: Optional[ReviewCfg] = None,
    ) -> QueryResponse: ...

    def dump(self) -> dict: ...

    def get_description_for_stat(self, stat_name: str) -> str: ...
