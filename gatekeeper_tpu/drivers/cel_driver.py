"""CEL driver — the k8scel equivalent.

Reference: pkg/drivers/k8scel/driver.go (engine name K8sNativeValidation).
``add_template`` compiles the template source (validations with
message/messageExpression, variables, matchConditions, failurePolicy —
schema/schema.go:28-61, reserved prefix ``gatekeeper_internal_``);
``query`` evaluates matchConditions then each validation per constraint with
the VAP binding environment: object / oldObject / request / params /
namespaceObject / variables.* (transform/cel_snippets.go binds
``variables.params`` and ``anyObject``).

DELETE normalization mirrors driver.go:184-186: on DELETE the bound
``object`` is null and ``oldObject`` carries the object.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ENGINE_CEL, ConstraintTemplate
from gatekeeper_tpu.client.types import QueryResponse, Result, Stat, StatsEntry
from gatekeeper_tpu.drivers.base import ReviewCfg
from gatekeeper_tpu.lang.cel.cel import (
    CelError,
    CelParseError,
    Env,
    Program,
    evaluate,
)
from gatekeeper_tpu.target.review import DELETE, GkReview

DRIVER_NAME = "K8sNativeValidation"
RESERVED_PREFIX = "gatekeeper_internal_"  # schema.go:21

# constant prelude ASTs (transform/cel_snippets.go), parsed once
_PARAMS_AST = Program("params").ast
_ANY_OBJECT_AST = Program("object != null ? object : oldObject").ast


class CELCompileError(Exception):
    pass


class _CompiledValidation:
    __slots__ = ("expression", "message", "message_expression")

    def __init__(self, expression: Program, message: str,
                 message_expression: Optional[Program]):
        self.expression = expression
        self.message = message
        self.message_expression = message_expression


class _CompiledCELTemplate:
    __slots__ = ("kind", "validations", "variables", "match_conditions",
                 "failure_policy", "generate_vap", "source")

    def __init__(self, kind, validations, variables, match_conditions,
                 failure_policy, generate_vap, source):
        self.kind = kind
        self.validations = validations
        self.variables = variables  # name -> AST
        self.match_conditions = match_conditions  # [(name, Program)]
        self.failure_policy = failure_policy
        self.generate_vap = generate_vap
        self.source = source


def parse_source(template: ConstraintTemplate) -> Optional[dict]:
    return template.targets[0].source_for(ENGINE_CEL)


def _vap_match_constraints(webhook_scope: Optional[dict]) -> dict:
    """matchConstraints for a generated VAP: the webhook's cached rules /
    selectors when known, else match-everything."""
    rules = (webhook_scope or {}).get("rules") or []
    resource_rules = [
        {"apiGroups": r.get("apiGroups", ["*"]),
         "apiVersions": r.get("apiVersions", ["*"]),
         "operations": r.get("operations", ["CREATE", "UPDATE"]),
         "resources": r.get("resources", ["*"])}
        for r in rules
    ] or [{
        "apiGroups": ["*"], "apiVersions": ["*"],
        "operations": ["CREATE", "UPDATE"], "resources": ["*"],
    }]
    out: dict = {"resourceRules": resource_rules}
    for sel in ("namespaceSelector", "objectSelector"):
        if (webhook_scope or {}).get(sel):
            out[sel] = webhook_scope[sel]
    return out


class CELDriver:
    def __init__(self, gather_stats: bool = False):
        self._templates: dict[str, _CompiledCELTemplate] = {}
        self.gather_stats = gather_stats

    def name(self) -> str:
        return DRIVER_NAME

    def has_source_for(self, template: ConstraintTemplate) -> bool:
        return parse_source(template) is not None

    # --- template lifecycle -------------------------------------------
    def compile_template(self, template: ConstraintTemplate) \
            -> "_CompiledCELTemplate":
        """Pure compile (no install) — the generation coordinator's
        staged-validation seam; ``add_template`` = compile + install."""
        source = parse_source(template)
        if source is None:
            raise CELCompileError(
                f"template {template.name}: no K8sNativeValidation source"
            )
        from gatekeeper_tpu.lang.cel.checker import check as cel_check

        try:
            validations = []
            for v in source.get("validations") or []:
                expr = v.get("expression", "")
                if not expr:
                    raise CELCompileError("validation with no expression")
                msg_expr = v.get("messageExpression")
                # static check (reference: cel-go type checker at
                # AddTemplate): unknown functions/idents fail admission
                cel_check(expr)
                if msg_expr:
                    cel_check(msg_expr)
                validations.append(_CompiledValidation(
                    Program(expr),
                    v.get("message", "") or "",
                    Program(msg_expr) if msg_expr else None,
                ))
            if not validations:
                raise CELCompileError("no validations")
            variables = {}
            for var in source.get("variables") or []:
                vname = var.get("name", "")
                if vname.startswith(RESERVED_PREFIX):
                    raise CELCompileError(
                        f"variable {vname!r} uses the reserved prefix "
                        f"{RESERVED_PREFIX!r}"
                    )
                cel_check(var.get("expression", ""))
                variables[vname] = Program(var.get("expression", "")).ast
            match_conditions = []
            for mc in (source.get("matchCondition")
                       or source.get("matchConditions") or []):
                cel_check(mc.get("expression", ""))
                match_conditions.append(
                    (mc.get("name", ""), Program(mc.get("expression", ""))))
            failure_policy = source.get("failurePolicy") or "Fail"
        except CelParseError as e:
            raise CELCompileError(
                f"template {template.name}: {e}"
            ) from e
        return _CompiledCELTemplate(
            template.kind, validations, variables, match_conditions,
            failure_policy, bool(source.get("generateVAP", False)), source,
        )

    def add_template(self, template: ConstraintTemplate) -> None:
        self._templates[template.kind] = self.compile_template(template)

    def remove_template(self, template_kind: str) -> None:
        self._templates.pop(template_kind, None)

    def add_constraint(self, constraint: Constraint) -> None:
        if constraint.kind not in self._templates:
            raise CELCompileError(
                f"no template for constraint kind {constraint.kind}"
            )

    def remove_constraint(self, constraint: Constraint) -> None:
        pass

    # --- data plane: CEL has no referential data (driver.go has no-op
    # AddData; inventory is a Rego-engine feature) ----------------------
    def add_data(self, target, path, data) -> None:
        pass

    def remove_data(self, target, path) -> None:
        pass

    # --- query ---------------------------------------------------------
    def query(
        self,
        target: str,
        constraints: Sequence[Constraint],
        review: GkReview,
        cfg: Optional[ReviewCfg] = None,
    ) -> QueryResponse:
        cfg = cfg or ReviewCfg()
        resp = QueryResponse()
        req = review.request
        obj = req.object
        old_obj = req.old_object
        if req.operation == DELETE:
            # driver.go:184-186: on DELETE, object is unset for CEL
            obj, old_obj = None, old_obj if old_obj is not None else req.object
        request_doc = req.to_review_doc(review.namespace)
        base_bindings = {
            "object": obj,
            "oldObject": old_obj,
            "request": request_doc,
            "namespaceObject": review.namespace,
            "anyObject": obj if obj is not None else old_obj,
        }
        for constraint in constraints:
            compiled = self._templates.get(constraint.kind)
            if compiled is None:
                continue
            t0 = time.perf_counter_ns()
            params = constraint.parameters if constraint.parameters is not None else {}
            bindings = dict(base_bindings)
            bindings["params"] = params
            lazy = dict(compiled.variables)
            lazy["params"] = _PARAMS_AST
            lazy["anyObject"] = _ANY_OBJECT_AST
            # one Env per (constraint, review): variables.* memoize across
            # matchConditions and validations, like the apiserver's
            # per-request variable bindings
            env = Env(bindings, lazy)

            try:
                if not self._match_conditions_pass(compiled, env):
                    continue
            except CelError as e:
                if compiled.failure_policy == "Fail":
                    resp.results.append(self._violation(
                        target, constraint,
                        f"matchCondition error: {e}"))
                continue

            for v in compiled.validations:
                try:
                    ok = evaluate(v.expression.ast, env)
                except CelError as e:
                    if compiled.failure_policy == "Fail":
                        resp.results.append(self._violation(
                            target, constraint,
                            f"validation error: {e}"))
                    continue
                if ok is True:
                    continue
                # messageExpression wins over static message when it yields a
                # non-empty string (VAP semantics)
                msg = ""
                if v.message_expression is not None:
                    try:
                        rendered = evaluate(v.message_expression.ast, env)
                        if isinstance(rendered, str):
                            msg = rendered
                    except CelError:
                        msg = ""
                if not msg:
                    msg = v.message
                if not msg:
                    msg = f"failed expression: {v.expression.src.strip()}"
                resp.results.append(self._violation(target, constraint, msg))
            if self.gather_stats or cfg.stats:
                resp.stats_entries.append(StatsEntry(
                    scope="constraint",
                    stats_for=f"{constraint.kind}/{constraint.name}",
                    stats=[Stat("templateRunTimeNS",
                                time.perf_counter_ns() - t0,
                                {"type": "engine", "value": DRIVER_NAME})],
                ))
        return resp

    @staticmethod
    def _match_conditions_pass(compiled, env) -> bool:
        for _name, prog in compiled.match_conditions:
            v = evaluate(prog.ast, env)
            if v is not True:
                return False
        return True

    @staticmethod
    def _violation(target, constraint, msg) -> Result:
        return Result(target=target, msg=msg, constraint=constraint.raw)

    def dump(self) -> dict:
        return {"templates": sorted(self._templates)}

    def get_description_for_stat(self, stat_name: str) -> str:
        return {
            "templateRunTimeNS": "the number of nanoseconds it took to "
            "evaluate all constraints for a template",
        }.get(stat_name, "unknown stat")

    # --- VAP codegen (reference: k8scel/transform/make_vap_objects.go) --
    def template_to_vap(self, template: ConstraintTemplate,
                        webhook_scope: Optional[dict] = None) -> dict:
        """Lower a CEL template to a native ValidatingAdmissionPolicy.
        ``webhook_scope`` (from the webhookconfig cache) mirrors the
        validating webhook's match scope into matchConstraints so the VAP
        enforces exactly where the webhook would (reference:
        webhookconfig_controller.go:293 scope sync)."""
        compiled = self._templates.get(template.kind)
        source = compiled.source if compiled else parse_source(template)
        if source is None:
            raise CELCompileError(
                f"template {template.name} has no K8sNativeValidation source"
            )
        variables = [
            {"name": "params",
             "expression": (
                 "!has(params.spec) ? null : !has(params.spec.parameters) ? "
                 "null : params.spec.parameters"
             )},
            {"name": "anyObject",
             "expression": "object != null ? object : oldObject"},
        ] + [
            {"name": v.get("name", ""), "expression": v.get("expression", "")}
            for v in (source.get("variables") or [])
        ]
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingAdmissionPolicy",
            "metadata": {"name": f"gatekeeper-{template.name}"},
            "spec": {
                "failurePolicy": source.get("failurePolicy") or "Fail",
                "paramKind": {
                    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                    "kind": template.kind,
                },
                "matchConstraints": _vap_match_constraints(webhook_scope),
                "matchConditions": [
                    {"name": mc.get("name", ""),
                     "expression": mc.get("expression", "")}
                    for mc in (source.get("matchCondition")
                               or source.get("matchConditions") or [])
                ],
                "validations": [
                    {k: v for k, v in (
                        ("expression", val.get("expression", "")),
                        ("message", val.get("message", "")),
                        ("messageExpression",
                         val.get("messageExpression", "")),
                    ) if v}
                    for val in (source.get("validations") or [])
                ],
                "variables": variables,
            },
        }

    def constraint_to_vap_binding(self, constraint: Constraint,
                                  template: ConstraintTemplate) -> dict:
        """Reference: transform.GetVAPBindingName + constraint_controller.go:375."""
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingAdmissionPolicyBinding",
            "metadata": {
                "name": f"gatekeeper-{constraint.name}"
            },
            "spec": {
                "policyName": f"gatekeeper-{template.name}",
                "paramRef": {
                    "name": constraint.name,
                    "parameterNotFoundAction": "Allow",
                },
                "validationActions": ["Deny"],
            },
        }
