"""Interpreter-backed Rego driver — the exact engine.

Plays the role of the reference's OPA rego driver (external module, usage
surface documented in SURVEY.md §2.8): template sources compile at
``add_template`` time, referential data lives under ``data.inventory.<path>``
(externs gate, main.go:474-478), and ``query`` evaluates the template's
``violation`` partial-set rule once per constraint with
``input = {review, parameters}``.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ENGINE_REGO, ConstraintTemplate
from gatekeeper_tpu.client.types import QueryResponse, Result, Stat, StatsEntry
from gatekeeper_tpu.drivers.base import ReviewCfg
from gatekeeper_tpu.lang.rego.interp import Interpreter, ModuleSet, compile_modules
from gatekeeper_tpu.lang.rego.value import to_json
from gatekeeper_tpu.target.review import GkReview

DRIVER_NAME = "Rego"


class TemplateCompileError(Exception):
    pass


class _CompiledTemplate:
    __slots__ = ("kind", "modules", "package")

    def __init__(self, kind: str, modules: ModuleSet, package: tuple):
        self.kind = kind
        self.modules = modules
        self.package = package


class RegoDriver:
    def __init__(self, trace_enabled: bool = False):
        self._templates: dict[str, _CompiledTemplate] = {}
        self._data: dict = {}  # inventory tree
        self._trace_enabled = trace_enabled

    def name(self) -> str:
        return DRIVER_NAME

    # --- template lifecycle -------------------------------------------
    def has_source_for(self, template: ConstraintTemplate) -> bool:
        return template.targets[0].source_for(ENGINE_REGO) is not None

    def compile_template(self, template: ConstraintTemplate) \
            -> _CompiledTemplate:
        """Pure compile (no install): the artifact ``add_template`` would
        store.  The generation coordinator uses this to validate a staged
        template synchronously while deferring the install to the swap."""
        src = template.targets[0].source_for(ENGINE_REGO)
        if src is None:
            raise TemplateCompileError(
                f"template {template.name}: no Rego source"
            )
        try:
            modules = compile_modules([src["rego"], *src.get("libs", [])])
        except SyntaxError as e:
            raise TemplateCompileError(
                f"template {template.name}: {e}"
            ) from e
        # entry module: the one holding the `violation` rule; by convention the
        # first source (the framework requires the entry rule in the template
        # body, not libs)
        from gatekeeper_tpu.lang.rego.parser import parse_module

        entry_pkg = parse_module(src["rego"]).package
        entry_mod = modules.by_pkg.get(entry_pkg)
        if entry_mod is None or "violation" not in entry_mod.rules:
            raise TemplateCompileError(
                f"template {template.name}: no violation rule in package "
                f"{'.'.join(entry_pkg)}"
            )
        return _CompiledTemplate(template.kind, modules, entry_pkg)

    def add_template(self, template: ConstraintTemplate) -> None:
        self._templates[template.kind] = self.compile_template(template)

    def remove_template(self, template_kind: str) -> None:
        self._templates.pop(template_kind, None)

    def add_constraint(self, constraint: Constraint) -> None:
        # Interpreter reads parameters straight off the constraint at query
        # time; nothing to precompute.
        if constraint.kind not in self._templates:
            raise TemplateCompileError(
                f"no template for constraint kind {constraint.kind}"
            )

    def remove_constraint(self, constraint: Constraint) -> None:
        pass

    # --- data plane ---------------------------------------------------
    def add_data(self, target: str, path: Sequence[str], data: Any) -> None:
        import copy

        node = self._data.setdefault("inventory", {})
        for p in path[:-1]:
            node = node.setdefault(p, {})
        # independent copy: OPA's store snapshots data on write; callers may
        # mutate the object afterwards (gator expand mutates bases in place)
        node[path[-1]] = copy.deepcopy(data)

    def remove_data(self, target: str, path: Sequence[str]) -> None:
        node = self._data.get("inventory")
        if node is None:
            return
        for p in path[:-1]:
            node = node.get(p)
            if not isinstance(node, dict):
                return
        node.pop(path[-1], None)

    def wipe_data(self) -> None:
        self._data.pop("inventory", None)

    # --- query --------------------------------------------------------
    def query(
        self,
        target: str,
        constraints: Sequence[Constraint],
        review: GkReview,
        cfg: Optional[ReviewCfg] = None,
        data_override: Optional[dict] = None,
    ) -> QueryResponse:
        """``data_override`` substitutes the data document for this query
        (the TPU driver's restricted-inventory render path)."""
        cfg = cfg or ReviewCfg()
        resp = QueryResponse()
        trace_lines: list[str] = [] if (cfg.tracing or self._trace_enabled) else None
        review_doc = review.request.to_review_doc(review.namespace)
        for constraint in constraints:
            compiled = self._templates.get(constraint.kind)
            if compiled is None:
                continue
            input_doc = {
                "review": review_doc,
                "parameters": constraint.parameters
                if constraint.parameters is not None
                else {},
            }
            interp = Interpreter(
                compiled.modules,
                data=self._data if data_override is None else data_override,
            )
            t0 = time.perf_counter_ns()
            violations = interp.query_set_rule(
                compiled.package, "violation", input_doc
            )
            elapsed = time.perf_counter_ns() - t0
            for v in violations:
                if isinstance(v, dict):
                    msg = v.get("msg", "")
                    details = to_json(v.get("details")) if "details" in v else None
                else:
                    msg, details = str(v), None
                metadata = {"details": details} if details is not None else {}
                resp.results.append(
                    Result(
                        target=target,
                        msg=msg if isinstance(msg, str) else str(msg),
                        constraint=constraint.raw,
                        metadata=metadata,
                    )
                )
            if cfg.stats:
                resp.stats_entries.append(
                    StatsEntry(
                        scope="constraint",
                        stats_for=f"{constraint.kind}/{constraint.name}",
                        stats=[
                            Stat(
                                name="templateRunTimeNS",
                                value=elapsed,
                                source={"type": "engine", "value": DRIVER_NAME},
                            ),
                            Stat(
                                name="constraintCount",
                                value=len(constraints),
                                source={"type": "engine", "value": DRIVER_NAME},
                            ),
                        ],
                    )
                )
            if trace_lines is not None:
                trace_lines.append(
                    f"constraint {constraint.kind}/{constraint.name}: "
                    f"{len(violations)} violation(s) in {elapsed}ns"
                )
        if trace_lines is not None:
            resp.trace = "\n".join(trace_lines)
        return resp

    def dump(self) -> dict:
        return {
            "templates": sorted(self._templates),
            "data": self._data,
        }

    def get_description_for_stat(self, stat_name: str) -> str:
        return {
            "templateRunTimeNS": "the number of nanoseconds it took to evaluate"
            " all constraints for a template",
            "constraintCount": "the number of constraints that were evaluated "
            "for the given constraint kind",
        }.get(stat_name, "unknown stat")
