"""Client side of the Evaluate sidecar seam.

``RemoteDriver`` implements the Driver protocol by replicating template/
constraint/data lifecycle into the sidecar (Reconcile) and evaluating via
QueryBatch — the control-plane process never touches the accelerator.
``RemoteEvaluator`` is the audit chunk lane: one Sweep RPC per chunk,
returning rendered kept violations + totals (the whole audit middle runs
device-side; ref shape pkg/audit/manager.go:668-774 collapsed into one
call).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional, Sequence

import grpc

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.client.types import QueryResponse, Result
from gatekeeper_tpu.drivers.base import ReviewCfg
from gatekeeper_tpu.rpc import SERVICE, load_pb2
from gatekeeper_tpu.target.review import GkReview

pb = load_pb2()

DRIVER_NAME = "TPU-remote"


class RemoteError(Exception):
    pass


class _Stub:
    """Hand-rolled unary stubs (no grpc_tools plugin in this image)."""

    def __init__(self, channel: grpc.Channel):
        def unary(method, req_cls, resp_cls):
            return channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )

        self.reconcile = unary("Reconcile", pb.ReconcileRequest,
                               pb.ReconcileResponse)
        self.query_batch = unary("QueryBatch", pb.QueryBatchRequest,
                                 pb.QueryBatchResponse)
        self.sweep = unary("Sweep", pb.SweepRequest, pb.SweepResponse)
        self.status = unary("Status", pb.StatusRequest, pb.StatusResponse)


def _review_to_pb(review: GkReview) -> "pb.Review":
    req = review.request
    doc = {
        "uid": req.uid, "kind": req.kind, "resource": req.resource,
        "subResource": req.sub_resource, "name": req.name,
        "namespace": req.namespace, "operation": req.operation,
        "userInfo": req.user_info, "object": req.object,
        "oldObject": req.old_object, "dryRun": req.dry_run,
        "options": req.options,
    }
    out = pb.Review(admission_request_json=json.dumps(doc).encode(),
                    source=getattr(review, "source", "") or "",
                    is_admission=bool(getattr(review, "is_admission",
                                              False)))
    if review.namespace:  # the Namespace OBJECT (GkReview.namespace)
        out.namespace_json = json.dumps(review.namespace).encode()
    return out


def _results_from_pb(rr, target: str) -> list:
    out = []
    for r in rr.results:
        metadata = {}
        if r.details_json:
            metadata["details"] = json.loads(r.details_json)
        out.append(Result(
            target=target,
            msg=r.msg,
            constraint=json.loads(r.constraint_json or b"{}"),
            metadata=metadata,
        ))
    return out


class RemoteDriver:
    """Driver protocol over the Evaluate sidecar."""

    def __init__(self, address: str, timeout_s: float = 120.0):
        self.address = address
        self.timeout_s = timeout_s
        self._channel = grpc.insecure_channel(
            address,
            options=[("grpc.max_receive_message_length",
                      256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)],
        )
        self._stub = _Stub(self._channel)
        self._lowered: list = []
        self._lock = threading.RLock()

    def name(self) -> str:
        return DRIVER_NAME

    # --- lifecycle (replicated to the sidecar) ------------------------
    def has_source_for(self, template: ConstraintTemplate) -> bool:
        # static source check: rego or K8sNativeValidation (mirrors the
        # sidecar's TpuDriver+CELDriver acceptance without compiling)
        from gatekeeper_tpu.apis.templates import ENGINE_REGO
        from gatekeeper_tpu.drivers.cel_driver import parse_source

        if template.targets[0].source_for(ENGINE_REGO) is not None:
            return True
        return parse_source(template) is not None

    def _reconcile(self, **kwargs) -> "pb.ReconcileResponse":
        resp = self._stub.reconcile(pb.ReconcileRequest(**kwargs),
                                    timeout=self.timeout_s)
        if resp.error:
            raise RemoteError(resp.error)
        with self._lock:
            self._lowered = list(resp.lowered)
        return resp

    def add_template(self, template: ConstraintTemplate) -> None:
        self._reconcile(verb="add_template",
                        object_json=json.dumps(template.raw).encode())

    def remove_template(self, template_kind: str) -> None:
        self._reconcile(verb="remove_template", kind=template_kind)

    def add_constraint(self, constraint: Constraint) -> None:
        self._reconcile(verb="add_constraint",
                        object_json=json.dumps(constraint.raw).encode())

    def remove_constraint(self, constraint: Constraint) -> None:
        self._reconcile(verb="remove_constraint",
                        object_json=json.dumps(constraint.raw).encode())

    def add_data(self, target: str, path: Sequence[str],
                 data: Any) -> None:
        self._reconcile(verb="add_data", path=list(path),
                        object_json=json.dumps(data).encode())

    def remove_data(self, target: str, path: Sequence[str]) -> None:
        self._reconcile(verb="remove_data", path=list(path))

    def wipe_data(self) -> None:
        self._reconcile(verb="wipe_data")

    # --- evaluation ---------------------------------------------------
    def query(self, target, constraints, review, cfg=None) -> QueryResponse:
        responses = self.query_batch(target, constraints, [review], cfg)
        return responses[0]

    def query_batch(self, target: str, constraints, reviews,
                    cfg: Optional[ReviewCfg] = None,
                    render_messages: bool = True) -> list:
        cfg = cfg or ReviewCfg()
        req = pb.QueryBatchRequest(
            enforcement_point=cfg.enforcement_point or "",
            render_messages=render_messages,
        )
        # restrict evaluation to the caller's constraint slice server-side
        # (per-request device work must not scale with the full set)
        req.constraint_keys.extend(
            f"{c.kind}/{c.name}" for c in constraints)
        req.reviews.extend(_review_to_pb(r) for r in reviews)
        resp = self._stub.query_batch(req, timeout=self.timeout_s)
        if resp.error:
            raise RemoteError(resp.error)
        want = {(c.kind, c.name) for c in constraints}
        out = []
        for rr in resp.responses:
            qr = QueryResponse()
            for r in _results_from_pb(rr, target):
                ckind = r.constraint.get("kind", "")
                cname = (r.constraint.get("metadata") or {}).get("name", "")
                # the sidecar evaluates its full constraint set; filter to
                # the caller's slice (Driver.Query contract)
                if (ckind, cname) in want:
                    qr.results.append(r)
            out.append(qr)
        return out

    def lowered_kinds(self) -> list:
        status = self._stub.status(pb.StatusRequest(),
                                   timeout=self.timeout_s)
        return list(status.lowered)

    def fallback_kinds(self) -> dict:
        status = self._stub.status(pb.StatusRequest(),
                                   timeout=self.timeout_s)
        return dict(status.fallback)

    def dump(self) -> dict:
        status = self._stub.status(pb.StatusRequest(),
                                   timeout=self.timeout_s)
        return {
            "lowered": list(status.lowered),
            "fallback": dict(status.fallback),
            "sidecar": {"devices": status.n_devices,
                        "platform": status.platform},
        }

    def get_description_for_stat(self, stat_name: str) -> str:
        return ""

    def close(self):
        self._channel.close()


class RemoteEvaluator:
    """Audit chunk lane over the sidecar: sweep_submit dispatches the RPC
    on a thread (pipelining with the host's next-chunk prep, like the
    local evaluator's async jit dispatch); sweep_collect joins it.

    ``renders = True``: responses carry rendered kept violations +
    totals, so the AuditManager folds them directly instead of rendering
    host-side."""

    renders = True

    def __init__(self, driver: RemoteDriver, violations_limit: int = 20,
                 exact_totals: bool = False):
        self.driver = driver
        self.violations_limit = violations_limit
        self.exact_totals = exact_totals

    def sweep_submit(self, constraints, objects, return_bits=False):
        req = pb.SweepRequest(
            violations_limit=self.violations_limit,
            exact_totals=return_bits or self.exact_totals,
        )
        # restrict the sweep to the caller's constraint slice (the audit
        # passes only audit-actionable constraints)
        req.constraint_keys.extend(
            f"{c.kind}/{c.name}" for c in constraints)
        req.object_json.extend(
            json.dumps(o).encode() for o in objects)
        return self.driver._stub.sweep.future(
            req, timeout=self.driver.timeout_s)

    def sweep_collect(self, pending):
        if pending is None or isinstance(pending, dict):
            return pending or {}
        resp = pending.result()
        if resp.error:
            raise RemoteError(resp.error)
        out = {}
        for cs in resp.constraints:
            kept = [
                (kv.object_index, kv.msg,
                 json.loads(kv.details_json) if kv.details_json else None)
                for kv in cs.kept
            ]
            out[(cs.kind, cs.name)] = (int(cs.total), kept)
        return out

    def sweep(self, constraints, objects, return_bits=False):
        return self.sweep_collect(
            self.sweep_submit(constraints, objects, return_bits))
