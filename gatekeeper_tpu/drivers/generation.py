"""Generations: background compile, executable swap, on-disk compile cache.

A **generation** is an immutable compilation unit — the ``(template set,
union schema, vocab snapshot)`` the serving paths evaluate with.  Today a
``ConstraintTemplate`` add/edit recompiles on the serving path (lowering +
union-schema reshape + jit retrace all land inside ``add_template``), so a
template-churn storm stalls admissions.  With ``--generation-swap on`` the
:class:`GenerationCoordinator` moves that work off the hot path:

- template/constraint mutations *stage* (cheap synchronous validation only
  — parse + interpreter/CEL compile, so reconcile status and readiness
  semantics are unchanged) and enqueue a background build;
- the background thread lowers the changed templates against the *current*
  vocab (the vocab is append-only, so programs of the old generation stay
  valid while the new one builds), reuses unchanged programs by source
  digest, warms the changed kernels with one ``warm_pass``-shaped
  dispatch, then **atomically swaps** the serving dicts;
- the webhook, audit sweep and mutation lane keep serving the old
  generation until the swap, and in-flight batches finish on the
  generation they started on (they capture the program dict once — swap
  replaces dict objects, never mutates them).

The :class:`CompileCache` persists lowering results to disk, keyed by
``(template digest, engine, jax/jaxlib version,``
``ops.flatten.FLATTEN_SCHEMA_VERSION, cache format)``.  Each entry also
records the full vocab string snapshot at lowering completion: loading
replays the snapshot (append-only interning), and an entry whose snapshot
is not reachable from the current vocab state (different template order, a
process that already interned conflicting strings) is a miss — baked sids
can never silently point at the wrong strings.  Corrupted or
version-drifted entries are rejected (and deleted) on load, never served.
``--compile-cache DIR`` also points JAX's persistent compilation cache at
``DIR/xla`` so XLA executable builds survive restarts too.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Optional

from gatekeeper_tpu.ops.flatten import FLATTEN_SCHEMA_VERSION

# bump when the on-disk payload layout changes
CACHE_FORMAT = 1

# miss reasons for gatekeeper_generation_cache_miss_count{reason}
MISS_COLD = "cold"          # no entry on disk
MISS_CORRUPT = "corrupt"    # unreadable meta / payload hash or pickle fail
MISS_DIGEST = "digest"      # entry's recorded key fields disagree
MISS_SCHEMA = "schema"      # program schema digest != recorded
MISS_VOCAB = "vocab"        # vocab snapshot not replayable here


def template_digest(template) -> str:
    """Content digest of one template — the per-kind cache/reuse key.
    Canonical JSON over the raw object's spec (the compilation input);
    programmatically-built templates without a raw doc fall back to the
    parsed fields."""
    raw = getattr(template, "raw", None) or {}
    doc: Any = raw.get("spec") if isinstance(raw, dict) else None
    if not doc:
        doc = {
            "name": template.name,
            "kind": template.kind,
            "schema": template.parameters_schema,
            "targets": [getattr(t, "raw", None) or repr(t)
                        for t in template.targets],
        }
    blob = json.dumps(doc, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def template_set_digest(digests) -> str:
    """Digest of a whole template set (order-independent) — the
    generation identity exported on the ``compile.generation`` span."""
    blob = "\n".join(sorted(digests))
    return hashlib.sha256(blob.encode()).hexdigest()


def schema_digest(schema) -> str:
    """Stable digest of a lowered program's (or a union) Schema — a
    load-time integrity check on cached entries: a payload whose
    unpickled schema does not reproduce the digest recorded at store
    time is rejected."""
    if schema is None:
        return "none"
    parts = (schema.scalars, schema.raggeds, schema.keysets,
             getattr(schema, "ragged_keysets", []),
             getattr(schema, "map_keys", []),
             getattr(schema, "parent_idx", []),
             getattr(schema, "canons", []),
             getattr(schema, "extra_axes", []))
    return hashlib.sha256(repr(parts).encode()).hexdigest()


class CompileCache:
    """On-disk lowering cache (one entry per template content digest).

    Key anatomy (all baked into the entry file name, so any drift is a
    clean miss, and re-validated from the meta on load, so a tampered or
    hash-collided entry is rejected):

    ``sha256(template digest | engine | jax version | jaxlib version |``
    ``flatten-schema version | cache format)``

    Entry = ``<key>.json`` (meta: key fields, payload sha256, schema
    digest) + ``<key>.pkl`` (pickled program-or-error + the vocab string
    snapshot).  Writes are tmp-file + rename, so a crashed writer leaves
    no half entry.
    """

    def __init__(self, root: str, metrics=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.metrics = metrics
        self.hits = 0
        self.misses = 0
        self.miss_reasons: dict = {}
        self.stores = 0

    # --- keys ----------------------------------------------------------
    @staticmethod
    def _versions() -> tuple:
        import jax

        try:
            import jaxlib

            jl = getattr(jaxlib, "__version__", "?")
        except Exception:
            jl = "?"
        return jax.__version__, jl

    def entry_key(self, tdigest: str, engine: str) -> str:
        jv, jlv = self._versions()
        blob = "|".join([tdigest, engine, jv, jlv,
                         str(FLATTEN_SCHEMA_VERSION), str(CACHE_FORMAT)])
        return hashlib.sha256(blob.encode()).hexdigest()[:40]

    def xla_cache_dir(self) -> str:
        """Subdirectory for JAX's persistent compilation cache (XLA
        executables) — enabled by ``__main__`` next to the lowering
        entries so one ``--compile-cache DIR`` covers both."""
        return os.path.join(self.root, "xla")

    def _paths(self, key: str) -> tuple:
        return (os.path.join(self.root, key + ".json"),
                os.path.join(self.root, key + ".pkl"))

    # --- accounting ----------------------------------------------------
    def _count(self, hit: bool, reason: str = "") -> None:
        if hit:
            self.hits += 1
        else:
            self.misses += 1
            self.miss_reasons[reason] = \
                self.miss_reasons.get(reason, 0) + 1
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            if hit:
                self.metrics.inc_counter(M.GENERATION_CACHE_HIT)
            else:
                self.metrics.inc_counter(M.GENERATION_CACHE_MISS,
                                         {"reason": reason})

    def _reject(self, key: str, reason: str) -> None:
        """A corrupted/stale entry is deleted so the rebuild can replace
        it — it must never be served."""
        self._count(False, reason)
        for p in self._paths(key):
            try:
                os.remove(p)
            except OSError:
                pass

    # --- load / store ---------------------------------------------------
    def get(self, tdigest: str, engine: str, vocab):
        """``("program", Program) | ("error", msg) | None``.

        A hit replays the entry's vocab snapshot into ``vocab`` (the
        current vocab state must be a prefix of the snapshot — identical
        template load order from a cold start always is), so every sid
        the cached program baked points at the same string here."""
        key = self.entry_key(tdigest, engine)
        meta_p, payload_p = self._paths(key)
        if not (os.path.exists(meta_p) and os.path.exists(payload_p)):
            self._count(False, MISS_COLD)
            return None
        try:
            with open(meta_p) as f:
                meta = json.load(f)
            with open(payload_p, "rb") as f:
                raw = f.read()
        except Exception:
            self._reject(key, MISS_CORRUPT)
            return None
        jv, jlv = self._versions()
        want = {"template_digest": tdigest, "engine": engine,
                "jax": jv, "jaxlib": jlv,
                "flatten_schema_version": FLATTEN_SCHEMA_VERSION,
                "format": CACHE_FORMAT}
        if any(meta.get(k) != v for k, v in want.items()):
            self._reject(key, MISS_DIGEST)
            return None
        if hashlib.sha256(raw).hexdigest() != meta.get("payload_sha256"):
            self._reject(key, MISS_CORRUPT)
            return None
        try:
            payload = pickle.loads(raw)
            program = payload["program"]
            error = payload["error"]
            snap = payload["vocab"]
        except Exception:
            self._reject(key, MISS_CORRUPT)
            return None
        if program is not None and \
                schema_digest(program.schema) != meta.get("schema_digest"):
            self._reject(key, MISS_SCHEMA)
            return None
        # vocab replay: current interned strings must be the snapshot's
        # prefix (same ids for everything already interned); then the
        # tail interns in recorded order, reproducing every baked sid
        cur = vocab._to_str
        if len(cur) > len(snap) or snap[: len(cur)] != cur:
            self._count(False, MISS_VOCAB)  # entry itself is fine
            return None
        for s in snap[len(cur):]:
            vocab.intern(s)
        self._count(True)
        if error is not None:
            return ("error", error)
        return ("program", program)

    def put(self, tdigest: str, engine: str, program, error: Optional[str],
            vocab) -> None:
        """Persist one lowering result (or its LowerError message) with
        the vocab snapshot at completion.  Best-effort: cache write
        failures never fail the compile."""
        key = self.entry_key(tdigest, engine)
        meta_p, payload_p = self._paths(key)
        jv, jlv = self._versions()
        try:
            raw = pickle.dumps({"program": program, "error": error,
                                "vocab": list(vocab._to_str)})
            meta = {"template_digest": tdigest, "engine": engine,
                    "jax": jv, "jaxlib": jlv,
                    "flatten_schema_version": FLATTEN_SCHEMA_VERSION,
                    "format": CACHE_FORMAT,
                    "payload_sha256": hashlib.sha256(raw).hexdigest(),
                    "schema_digest": (schema_digest(program.schema)
                                      if program is not None else "none"),
                    "stored_at": time.time()}
            tmp = payload_p + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, payload_p)
            tmp = meta_p + ".tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, meta_p)
            self.stores += 1
        except Exception:
            pass

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "miss_reasons": dict(self.miss_reasons),
                "stores": self.stores}


def warm_yield_s(cpu_count: Optional[int] = None) -> float:
    """Per-kernel cooperative-yield gap for the pre-swap warm.

    Tracing is GIL-held Python: on a 1-core host, back-to-back kernel
    traces starve the serving thread for the whole warm, so each trace
    leaves a bounded 5ms gap (the measured storm-P99 sweet spot —
    CHURN_BENCH pins 1-core behavior unchanged).  Hosts with spare
    cores need (almost) none: the serving thread runs on another core
    while the warm traces, and every gap only stretches the warm —
    which delays the swap the serving path is waiting on.  Few-core
    (2-3) hosts keep a token 1ms: the GIL is still shared even when
    the cores are not saturated."""
    n = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
    if n <= 1:
        return 0.005
    if n < 4:
        return 0.001
    return 0.0


def programs_digest(driver) -> str:
    """Digest of the installed compiled plan (kind -> program schema) —
    the warm-state cache key: recorded executable layouts only replay
    against the exact program schemas they were traced with."""
    parts = sorted((k, schema_digest(p.program.schema))
                   for k, p in driver._programs.items())
    return hashlib.sha256(repr(parts).encode()).hexdigest()


# bump when the warm-state payload layout changes
WARM_FORMAT = 1


def library_warm_dir(root: str, library_digest: str) -> str:
    """Per-library :class:`WarmStateCache` directory under one shared
    compile-cache root (fleet mode).  The lowering entries are
    template-digest-keyed, so N libraries SHARE the root — but warm
    state is one file per directory, validated against the
    installed-programs digest, so libraries sharing a root would
    overwrite each other's.  One subdir per template-set digest keeps
    every library's warm state resident beside the shared lowerings."""
    return os.path.join(root, "warm", (library_digest or "default")[:16])


class WarmStateCache:
    """Persisted warm execution state under the compile-cache dir.

    The compile cache (above) removes restart LOWERING; this removes the
    restart RETRACE: the fused sweep executables' trace descriptors +
    input avals (``ShardedEvaluator.warm_state``: recorded keys, corpus
    column stats, width targets, hit-buffer state) and the admission
    path's warm reference batch (``TpuDriver._warm_ref`` — the latest
    real admission batch, the only thing that traces kernels at the true
    serving shapes).  On boot, :meth:`replay` re-lands every trace off
    the serving path — with the persistent XLA cache answering the
    compiles — so a restarted process retraces nothing on its first
    tick or admission burst.

    Integrity mirrors :class:`CompileCache`: payload sha256 + format /
    jax / flatten-schema fields + the installed-programs digest in the
    meta; corrupt or drifted state is deleted and simply not replayed
    (the process falls back to lazy tracing — never wrong, just cold).
    """

    def __init__(self, root: str, metrics=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.metrics = metrics
        self.saves = 0
        self.loads = 0
        self.misses = 0

    def _paths(self) -> tuple:
        return (os.path.join(self.root, "warm_state.json"),
                os.path.join(self.root, "warm_state.pkl"))

    def save(self, driver, evaluator=None) -> bool:
        """Best-effort: a failed save never fails the caller (drain)."""
        meta_p, payload_p = self._paths()
        try:
            payload = {
                "sweeps": (evaluator.warm_state()
                           if evaluator is not None else None),
                "warm_ref": getattr(driver, "_warm_ref", None),
            }
            raw = pickle.dumps(payload)
            jv, jlv = CompileCache._versions()
            meta = {"format": WARM_FORMAT,
                    "flatten_schema_version": FLATTEN_SCHEMA_VERSION,
                    "jax": jv, "jaxlib": jlv,
                    "programs": programs_digest(driver),
                    "payload_sha256": hashlib.sha256(raw).hexdigest(),
                    "saved_at": time.time()}
            tmp = payload_p + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, payload_p)
            tmp = meta_p + ".tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, meta_p)
            self.saves += 1
            return True
        except Exception:
            return False

    def _reject(self) -> None:
        self.misses += 1
        for p in self._paths():
            try:
                os.remove(p)
            except OSError:
                pass

    def load(self, driver):
        """The validated payload, or None (corrupt/drifted state is
        deleted, never replayed)."""
        meta_p, payload_p = self._paths()
        if not (os.path.exists(meta_p) and os.path.exists(payload_p)):
            self.misses += 1
            return None
        try:
            with open(meta_p) as f:
                meta = json.load(f)
            with open(payload_p, "rb") as f:
                raw = f.read()
        except Exception:
            self._reject()
            return None
        jv, jlv = CompileCache._versions()
        want = {"format": WARM_FORMAT,
                "flatten_schema_version": FLATTEN_SCHEMA_VERSION,
                "jax": jv, "jaxlib": jlv,
                "programs": programs_digest(driver)}
        if any(meta.get(k) != v for k, v in want.items()):
            self._reject()
            return None
        if hashlib.sha256(raw).hexdigest() != meta.get("payload_sha256"):
            self._reject()
            return None
        try:
            payload = pickle.loads(raw)
        except Exception:
            self._reject()
            return None
        self.loads += 1
        return payload

    def replay(self, driver, evaluator=None) -> dict:
        """Load + re-land: sweep traces through
        ``ShardedEvaluator.replay_warm`` and — when a generation
        coordinator exists — the admission kernels through a
        ``warm_serving`` pass over the restored ``_warm_ref``."""
        payload = self.load(driver)
        if payload is None:
            return {"hit": False, "sweep_traces": 0}
        landed = 0
        if payload.get("sweeps") is not None and evaluator is not None:
            evaluator.restore_warm_state(payload["sweeps"])
            landed = evaluator.replay_warm()
        ref = payload.get("warm_ref")
        if ref is not None:
            driver._warm_ref = tuple(ref)
            if driver.gen_coord is not None:
                driver.gen_coord.warm_serving()
        return {"hit": True, "sweep_traces": landed}


class _Staged:
    """One staged template: synchronously-validated artifacts waiting for
    the next generation build."""

    __slots__ = ("template", "engine", "artifact", "digest")

    def __init__(self, template, engine: str, artifact, digest: str):
        self.template = template
        self.engine = engine  # "rego" | "cel"
        self.artifact = artifact  # interp/CEL compiled template
        self.digest = digest


class Generation:
    """One built (not necessarily yet swapped-in) generation."""

    __slots__ = ("gen_id", "programs", "lower_errors", "cel_kinds",
                 "interp_templates", "cel_templates", "set_digest",
                 "compile_seconds", "reused", "lowered_fresh",
                 "cache_hits")

    def __init__(self, gen_id: int):
        self.gen_id = gen_id
        self.programs: dict = {}       # kind -> CompiledProgram
        self.lower_errors: dict = {}   # kind -> why fallback
        self.cel_kinds: set = set()
        self.interp_templates: dict = {}  # kind -> rego _CompiledTemplate
        self.cel_templates: dict = {}     # kind -> _CompiledCELTemplate
        self.set_digest = ""
        self.compile_seconds = 0.0
        self.reused = 0         # programs carried over unchanged
        self.lowered_fresh = 0  # kinds actually lowered this build
        self.cache_hits = 0     # kinds answered by the disk cache


class GenerationCoordinator:
    """Owns the desired template set and the background compile thread.

    Until :meth:`start` is called (boot, --once runs, in-process tests)
    every mutation builds-and-swaps *inline* on the caller thread —
    byte-for-byte today's behavior, just routed through the generation
    build (so the compile cache serves boot loads too).  After
    :meth:`start`, mutations stage + notify and the thread coalesces a
    churn burst into one build."""

    def __init__(self, driver, cache: Optional[CompileCache] = None,
                 metrics=None, warm: bool = True):
        self.driver = driver
        self.cache = cache
        self.metrics = metrics
        self.warm = warm
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._desired: dict = {}   # kind -> _Staged (insertion order)
        self._installed_digests: dict = {}  # kind -> digest (serving gen)
        self._dirty = False
        self._building = False
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self.gen_id = 0
        self.swap_count = 0
        self.last_error: Optional[str] = None
        self.compile_count = 0
        # optional live-constraint source (e.g. Client.constraints): the
        # pre-swap warm then traces each changed kernel at the REAL
        # serving shape (param-table rows = that kind's constraint
        # count), so the first post-swap batch reuses the warm trace
        # instead of retracing on the serving thread
        self.constraints_fn = None
        # auxiliary compile units (the mutation lane's revision-keyed
        # programs ride the same background machinery):
        # name -> (current_key_fn, build_fn, install_fn, installed_key)
        self._aux: dict = {}
        # DeviceResidency instances to evict at swap (stale-HBM release;
        # see snapshot/device_residency.py)
        self._residencies: list = []

    def attach_residency(self, residency) -> None:
        """Register a snapshot DeviceResidency for proactive eviction at
        every generation swap (its mirrors were packed under the old
        programs' schemas)."""
        with self._lock:
            self._residencies.append(residency)

    # --- lifecycle ------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "GenerationCoordinator":
        """Go asynchronous: post-boot mutations compile off the serving
        path.  Also arms the vocab intern lock — the background thread
        interns against the live vocab."""
        with self._lock:
            if self.running:
                return self
            vocab = self.driver.vocab
            if getattr(vocab, "_lock", None) is None:
                vocab._lock = threading.RLock()
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name="generation-compile", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no build is pending or in flight (tests/benches:
        'quiesce, then assert verdicts')."""
        end = time.monotonic() + timeout
        with self._cv:
            while (self._dirty or self._building
                   or self._aux_dirty_locked()):
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.05))
        return True

    # --- aux compile units (mutlane) ------------------------------------
    def register_aux(self, name: str, current_key_fn, build_fn,
                     install_fn) -> None:
        with self._lock:
            self._aux[name] = [current_key_fn, build_fn, install_fn, None]

    def note_aux_dirty(self, name: str) -> None:
        with self._lock:
            self._cv.notify_all()

    def _aux_dirty_locked(self) -> bool:
        for key_fn, _b, _i, installed in self._aux.values():
            try:
                if key_fn() != installed:
                    return True
            except Exception:
                pass
        return False

    # --- staging (driver-facing) ----------------------------------------
    def submit_add(self, template) -> None:
        """Validate synchronously (parse/compile errors raise HERE, so
        reconcile status + readiness behave exactly as inline compile),
        stage, and either notify the background thread or — when it is
        not running — build + swap inline."""
        driver = self.driver
        if not driver._interp.has_source_for(template) and \
                driver._cel is not None and \
                driver._cel.has_source_for(template):
            engine = "cel"
            artifact = driver._cel.compile_template(template)
        else:
            engine = "rego"
            artifact = driver._interp.compile_template(template)
        staged = _Staged(template, engine, artifact,
                         template_digest(template))
        with self._lock:
            self._desired.pop(template.kind, None)
            self._desired[template.kind] = staged
            self._dirty = True
            if self.running:
                self._cv.notify_all()
                return
        self._build_and_swap()

    def submit_remove(self, kind: str) -> None:
        with self._lock:
            self._desired.pop(kind, None)
            self._dirty = True
            if self.running:
                self._cv.notify_all()
                return
        self._build_and_swap()

    def is_staged(self, kind: str) -> bool:
        """True when the kind is in the desired set (serving or pending
        swap) — constraint adds for a staged-not-yet-swapped template
        must be accepted, not rejected as unknown."""
        with self._lock:
            return kind in self._desired

    # --- the background loop --------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not (self._dirty or self._stop
                           or self._aux_dirty_locked()):
                    self._cv.wait(0.5)
                if self._stop:
                    return
            try:
                self._build_and_swap()
            except Exception as e:
                # a failed build leaves the serving generation untouched;
                # the next churn event retries
                with self._lock:
                    self.last_error = str(e)

    def _build_and_swap(self) -> None:
        from gatekeeper_tpu.observability import tracing

        with self._lock:
            desired = dict(self._desired)
            template_dirty = self._dirty
            self._dirty = False
            self._building = True
            aux_work = [(name, entry) for name, entry in self._aux.items()]
        try:
            if template_dirty:
                t0 = time.perf_counter()
                with tracing.span("compile.generation",
                                  templates=len(desired)) as sp:
                    gen = self._build(desired)
                    gen.compile_seconds = time.perf_counter() - t0
                    sp.set_attribute("gen_id", gen.gen_id)
                    sp.set_attribute("reused", gen.reused)
                    sp.set_attribute("lowered", gen.lowered_fresh)
                    sp.set_attribute("cache_hits", gen.cache_hits)
                # warm only on the BACKGROUND lane: the point is that the
                # swap lands pre-traced executables while the old
                # generation still serves; an inline (pre-start) caller
                # is already on the serving path and boot warms anyway
                if self.warm and self.running:
                    self._warm(gen)
                self._swap(gen, desired)
            # aux units (mutlane): rebuild whichever drifted
            for name, entry in aux_work:
                key_fn, build_fn, install_fn = entry[0], entry[1], entry[2]
                try:
                    key = key_fn()
                except Exception:
                    continue
                if key == entry[3]:
                    continue
                with tracing.span("compile.generation", unit=name):
                    built = build_fn()
                install_fn(built)
                with self._lock:
                    entry[3] = key
        finally:
            with self._cv:
                self._building = False
                self._cv.notify_all()

    def _build(self, desired: dict) -> Generation:
        """Compile the next generation: reuse unchanged programs by
        source digest, answer changed kinds from the disk cache when the
        vocab snapshot replays, lower the rest.  The chaos seam
        ``compile.generation`` lets tests kill a build mid-flight and
        assert the serving generation survives."""
        from gatekeeper_tpu.resilience.faults import fault_point

        fault_point("compile.generation", n=len(desired))
        driver = self.driver
        with self._lock:
            gen = Generation(self.gen_id + 1)
        self.compile_count += 1
        serving_programs = driver._programs
        serving_errors = driver._lower_errors
        for kind, staged in desired.items():
            if staged.engine == "cel":
                gen.cel_kinds.add(kind)
                gen.cel_templates[kind] = staged.artifact
            else:
                gen.interp_templates[kind] = staged.artifact
            if self._installed_digests.get(kind) == staged.digest:
                # unchanged template: the serving program object (or its
                # recorded lowering error) carries over — the vocab is
                # append-only, so old programs stay valid forever
                if kind in serving_programs:
                    gen.programs[kind] = serving_programs[kind]
                    gen.reused += 1
                    continue
                if kind in serving_errors:
                    gen.lower_errors[kind] = serving_errors[kind]
                    gen.reused += 1
                    continue
            program, err, from_cache = driver._lower_staged(staged)
            if from_cache:
                gen.cache_hits += 1
            else:
                gen.lowered_fresh += 1
            if program is not None:
                gen.programs[kind] = program
            elif err is not None:
                gen.lower_errors[kind] = err
        gen.set_digest = template_set_digest(
            s.digest for s in desired.values())
        return gen

    def _warm(self, gen: Generation) -> None:
        """One warm_pass-shaped dispatch over the WHOLE next generation.

        Why every kind, not just the changed ones: the serving batch
        flattens under the union schema of all lowered kinds, and the
        flattener's prefix-axis dedup re-pads SHARED ragged columns
        when any template joins or leaves the union — so one edit can
        reshape every program's input avals (measured: one
        library-template removal retraced all 45 remaining kernels,
        ~4s on the serving thread).  Tracing happens here, on the
        compile thread, against the new union + the real constraint
        counts (``constraints_fn``); the post-swap serving burst then
        reuses these traces.  Param tables build for ALL kinds before
        any run so string-pred matrices bake their final row count
        (the warm_pass ordering rule).  Best-effort: warm failures
        must never block the swap."""
        from gatekeeper_tpu.apis.constraints import Constraint
        from gatekeeper_tpu.ir.program import build_param_table
        from gatekeeper_tpu.ops.flatten import Flattener, Schema

        driver = self.driver
        kinds = sorted(gen.programs)
        if not kinds:
            return
        try:
            cons_by_kind: dict = {}
            if self.constraints_fn is not None:
                try:
                    for c in self.constraints_fn():
                        cons_by_kind.setdefault(c.kind, []).append(c)
                except Exception:
                    cons_by_kind = {}
            schema = Schema()
            for kind in kinds:
                schema.merge(gen.programs[kind].program.schema)
            fl = Flattener(schema, driver.vocab)
            ref = getattr(driver, "_warm_ref", None)
            if ref is not None:
                # replay the latest REAL admission batch through the new
                # union: ragged pad widths are data-dependent, so only
                # real objects land the traces at the serving shapes
                objects, review_docs, pad_n = ref
                batch = fl.flatten(objects, pad_n=pad_n,
                                   reviews=review_docs)
            else:
                batch = fl.flatten([dict(_WARM_OBJ)],
                                   pad_n=driver.batch_bucket)
            tables = {}
            for kind in kinds:  # register every needle row before runs
                prog = gen.programs[kind]
                cons = cons_by_kind.get(kind) or [
                    Constraint(kind=kind, name="__gen_warm__", match={},
                               parameters={}, enforcement_action="deny")]
                tables[kind] = build_param_table(prog.program, cons,
                                                 driver.vocab)
            gap = warm_yield_s()
            for kind in kinds:
                prog = gen.programs[kind]
                prog.run(batch, tables[kind], vocab=driver.vocab,
                         extra_cols=driver.inventory_cols(
                             kind, programs=gen.programs)[0])
                # cooperative yield between kernel traces: tracing is
                # GIL-held Python, and on few-core hosts back-to-back
                # traces would otherwise starve the serving thread for
                # the whole warm — one bounded gap per kernel keeps the
                # storm P99 near one trace, not the sum of all of them.
                # Sized from the host's core count (warm_yield_s): on a
                # many-core host the serving thread runs on its own
                # core, so the gap only stretches the warm for nothing
                if gap:
                    time.sleep(gap)
        except Exception as e:
            with self._lock:
                self.last_error = f"warm: {e}"

    def warm_serving(self) -> None:
        """Warm the CURRENT serving generation at the persisted
        ``_warm_ref`` shapes — the WarmStateCache boot replay's
        admission-side half.  Runs :meth:`_warm` over a pseudo
        generation holding the serving programs; traces land on the
        caller (boot) thread before any traffic, so the first real
        admission burst retraces nothing."""
        gen = Generation(self.gen_id)
        gen.programs = dict(self.driver._programs)
        self._warm(gen)

    def _swap(self, gen: Generation, desired: dict) -> None:
        self.driver._install_generation(gen)
        with self._lock:
            self.gen_id = gen.gen_id
            self.swap_count += 1
            self.last_error = None
            self._installed_digests = {
                k: s.digest for k, s in desired.items()}
            residencies = list(self._residencies)
        # device-resident snapshot mirrors were packed under the OLD
        # generation's schemas: correctness is already covered (each
        # mirror's program-uid signature misses on next prepare), this
        # eviction just frees the stale HBM now instead of one tick later
        for res in residencies:
            try:
                res.invalidate()
            except Exception:
                pass
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as M

            self.metrics.set_gauge(M.GENERATION_ID, gen.gen_id)
            self.metrics.set_gauge(M.GENERATION_COMPILE_SECONDS,
                                   gen.compile_seconds)
            self.metrics.inc_counter(M.GENERATION_SWAP_COUNT)

    # --- introspection ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "gen_id": self.gen_id,
                "swap_count": self.swap_count,
                "pending": self._dirty or self._building,
                "templates": len(self._desired),
                "last_error": self.last_error,
                "background": self.running,
            }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out


# the warm object: a plausible small Pod — ragged container axes get a
# non-empty width so the warm flatten pads shared axes the way a real
# admission burst does (width buckets make wider bursts share the shape)
_WARM_OBJ = {
    "apiVersion": "v1", "kind": "Pod",
    "metadata": {"name": "generation-warm", "namespace": "default",
                 "labels": {"app": "warm"}},
    "spec": {"containers": [{"name": "c", "image": "warm:latest"}]},
}
