"""The TPU driver: vectorized detection + exact host rendering.

Registered beside the interpreter driver exactly as the reference registers
k8scel beside rego (main.go:465-485).  Split of labor:

- ``add_template`` compiles the Rego source twice: (a) interpreter modules
  (exact oracle + message rendering), (b) lowered predicate Program where the
  template is in the vectorizable fragment (ir/lower_rego).
- ``query`` (single review) delegates to the interpreter — a webhook-latency
  lane needs no device round-trip for N=1.
- ``query_batch`` (many reviews) is the TPU path: flatten once, compute match
  masks, run each lowered template's [C, N] verdict kernel on device, then
  render messages host-side by re-running the interpreter only on hits.
  Templates outside the fragment fall back to the interpreter loop for their
  matching (constraint, object) pairs — behind the same seam, per SURVEY.md §7
  "compile-or-fallback".

The verdict grid is exact by construction (differential tests) so hit
rendering never changes the violation set, only fills in msg/details.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import numpy as np

from gatekeeper_tpu.apis.constraints import Constraint
from gatekeeper_tpu.apis.templates import ConstraintTemplate
from gatekeeper_tpu.client.types import QueryResponse, Result, Stat, StatsEntry
from gatekeeper_tpu.drivers.base import ReviewCfg
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.ir import masks as masks_mod
from gatekeeper_tpu.ir.lower_rego import lower_template
from gatekeeper_tpu.ir.program import (CompiledProgram, LowerError,
                                        build_param_table, extdata_key_cols,
                                        walk_join_values)
from gatekeeper_tpu.ops.flatten import (K_STR, Flattener, Schema, Vocab,
                                        round_up)
from gatekeeper_tpu.target.review import GkReview

DRIVER_NAME = "TPU"


def _col_restrictable(col) -> bool:
    """True when ``_col_values`` can reproduce the column read on the raw
    object — object-rooted ScalarCol/RaggedCol only (review-level
    ``__review__`` columns have no object path to walk)."""
    from gatekeeper_tpu.ops.flatten import RaggedCol, ScalarCol

    if isinstance(col, ScalarCol):
        return col.path[:1] != ("__review__",)
    return isinstance(col, RaggedCol)


def _col_values(obj, col):
    """String values of a ScalarCol/RaggedCol read on the raw object —
    built on the flattener's own walk helpers so the restriction sees
    exactly the values the device columns held."""
    from gatekeeper_tpu.ops.flatten import (RaggedCol, ScalarCol,
                                            _axis_items, _walk)

    if isinstance(col, ScalarCol):
        val, ok = _walk(obj, col.path)
        return [val] if ok and isinstance(val, str) else []
    if isinstance(col, RaggedCol):
        out = []
        for item in _axis_items(obj, col.axis):
            val, ok = _walk(item, col.subpath)
            if ok and isinstance(val, str):
                out.append(val)
        return out
    return []


class TpuDriver:
    """Implements the Driver protocol + the batched device path.

    With a ``cel_driver``, CEL (K8sNativeValidation) templates are accepted
    too: their validations lower onto the same predicate IR
    (ir/lower_cel.py) and join the fused verdict sweep; the CEL evaluator
    remains the exact oracle and message renderer for those kinds — the
    same compile-or-fallback split the Rego path uses."""

    def __init__(self, batch_bucket: int = 256, cel_driver=None,
                 metrics=None, generation_swap: bool = False,
                 compile_cache=None):
        import threading

        self._interp = RegoDriver()
        self._cel = cel_driver  # optional CELDriver
        self._cel_kinds: set = set()  # kinds owned by the CEL engine
        self.vocab = Vocab()
        self._programs: dict[str, CompiledProgram] = {}  # kind -> compiled
        self._lower_errors: dict[str, str] = {}  # kind -> why fallback
        # on-disk lowering cache (drivers/generation.py CompileCache):
        # consulted by BOTH the inline path and generation builds, so
        # --once / gator restarts skip lowering with or without swap mode
        self._compile_cache = compile_cache
        # monotone epoch of the compiled plane: bumped on every template
        # install (inline or swap) — the evaluator's per-generation
        # schema/executable caches key on it
        self.plan_epoch = 0
        self._swap_lock = threading.Lock()
        # --generation-swap on: template mutations stage + compile on a
        # background thread and swap atomically; None = inline compile
        # (today's path, byte-for-byte)
        self.gen_coord = None
        # (objects, review_docs, pad_n) of the latest query_batch — the
        # generation warm's shape reference (generation mode only)
        self._warm_ref = None
        # (plan_epoch, union Schema) — the generation-pinned admission
        # union, merged once per swap (see _query_batch_impl)
        self._qb_schema = None
        if generation_swap:
            from gatekeeper_tpu.drivers.generation import \
                GenerationCoordinator

            self.gen_coord = GenerationCoordinator(
                self, cache=compile_cache, metrics=metrics)
        self._data_version = 0
        self._data_kind_versions: dict = {}  # inventory kind -> version
        self._inv_cache: dict = {}  # kind -> (versions, cols, exact)
        self._render_specs: dict = {}  # kind -> Optional[list[(spec, col)]]
        self._render_idx: dict = {}  # spec.key() -> (version, value -> entries)
        self._dev_cache: dict = {}  # host array id -> device array (bounded)
        # extdata/lane.ExtDataLane: explicit attachment wins over the
        # process-active lane (see _active_extdata)
        self.extdata_lane = None
        self.batch_bucket = batch_bucket
        # metrics.registry.MetricsRegistry (optional): lowering coverage
        # counters — a user template silently falling back to the
        # interpreter loses the device speedup, and nothing else reports it
        self.metrics = metrics
        # device->host transfer accounting for the webhook query_batch
        # lane (grid fetches), the admission-side twin of the audit
        # evaluator's perf["d2h_bytes"]; read by bench/ops tooling
        self.perf: dict = {}

    def _count_lowering(self, kind: str, engine: str, lowered: bool) -> None:
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as M

        self.metrics.inc_counter(
            M.LOWERING_LOWERED if lowered else M.LOWERING_FALLBACK,
            {"kind": kind, "engine": engine})

    def lowering_stats(self) -> dict:
        """Device-coverage summary for bench/CLI output: how much of the
        loaded template set actually rides the device verdict path."""
        lowered = len(self._programs)
        fallback = len(self._lower_errors)
        total = lowered + fallback
        return {
            "templates": total,
            "lowered": lowered,
            "fallback": fallback,
            "fallback_fraction": round(fallback / total, 4) if total else 0.0,
            "fallback_kinds": dict(self._lower_errors),
        }

    # --- Driver protocol (delegating lifecycle to the exact engine) ------
    def name(self) -> str:
        return DRIVER_NAME

    def has_source_for(self, template: ConstraintTemplate) -> bool:
        if self._interp.has_source_for(template):
            return True
        return self._cel is not None and self._cel.has_source_for(template)

    def add_template(self, template: ConstraintTemplate) -> None:
        if self.gen_coord is not None:
            # generation mode: synchronous validation + staged compile;
            # the serving executable is untouched until the swap
            self.gen_coord.submit_add(template)
            return
        if not self._interp.has_source_for(template) and \
                self._cel is not None and self._cel.has_source_for(template):
            self._add_cel_template(template)
            return
        self._interp.add_template(template)
        self._cel_kinds = self._cel_kinds - {template.kind}
        compiled = self._interp._templates[template.kind]
        program, err, _hit = self._lower_or_cached(
            template.kind, "rego", template,
            lambda: lower_template(
                compiled.modules,
                compiled.package,
                template.kind,
                self.vocab,
                schema_hint=template.parameters_schema,
            ))
        self._install_inline(template.kind, program, err, "rego")

    def _lower_or_cached(self, kind: str, engine: str, template,
                         lower_fn) -> tuple:
        """(CompiledProgram | None, lower-error | None, from_cache):
        answer from the on-disk compile cache when the entry's vocab
        snapshot replays here (zero lowering, zero trial), else lower +
        trial-build and persist the result (program or error)."""
        cache = self._compile_cache
        digest = ""
        if cache is not None:
            from gatekeeper_tpu.drivers.generation import template_digest

            digest = template_digest(template)
            hit = cache.get(digest, engine, self.vocab)
            if hit is not None:
                tag, val = hit
                if tag == "program":
                    return CompiledProgram(val), None, True
                return None, val, True
        try:
            program = lower_fn()
            self._trial_param_table(program, kind)
        except LowerError as e:
            if cache is not None:
                cache.put(digest, engine, None, str(e), self.vocab)
            return None, str(e), False
        if cache is not None:
            cache.put(digest, engine, program, None, self.vocab)
        return CompiledProgram(program), None, False

    # --- generation machinery (drivers/generation.py) -------------------
    def _lower_staged(self, staged) -> tuple:
        """Lower one staged template for a background generation build
        (serving state untouched).  Returns (program, err, from_cache)."""
        kind = staged.template.kind
        hint = staged.template.parameters_schema
        if staged.engine == "cel":
            from gatekeeper_tpu.ir.lower_cel import lower_cel_template

            def lower_fn():
                return lower_cel_template(staged.artifact, kind,
                                          self.vocab, schema_hint=hint)
        else:
            def lower_fn():
                return lower_template(staged.artifact.modules,
                                      staged.artifact.package, kind,
                                      self.vocab, schema_hint=hint)
        program, err, from_cache = self._lower_or_cached(
            kind, staged.engine, staged.template, lower_fn)
        self._count_lowering(kind, staged.engine, program is not None)
        return program, err, from_cache

    def _install_generation(self, gen) -> None:
        """The swap point: every serving structure is REPLACED with a
        fresh object (single attribute assignments under the swap lock),
        never mutated in place — in-flight batches that captured the old
        dicts finish on the generation they started on, and readers see
        either the old or the new generation, never a mix of one dict."""
        with self._swap_lock:
            self._interp._templates = dict(gen.interp_templates)
            if self._cel is not None:
                self._cel._templates = dict(gen.cel_templates)
            self._cel_kinds = set(gen.cel_kinds)
            self._programs = dict(gen.programs)
            self._lower_errors = dict(gen.lower_errors)
            self._inv_cache = {}
            self._render_specs = {}
            self._render_idx = {}
            self.plan_epoch += 1

    def _trial_param_table(self, program, kind: str) -> None:
        """Compile-time dry run of build_param_table with a synthetic
        empty constraint: structural table errors (e.g. an unbound
        param-list element needle the lowering missed) surface HERE as a
        LowerError — falling back to the exact engine — instead of
        erroring every query at serve time (ADVICE r2 high)."""
        trial = Constraint(kind=kind, name="__lower_trial__", match={},
                           parameters={}, enforcement_action="deny")
        build_param_table(program, [trial], self.vocab)

    def _add_cel_template(self, template: ConstraintTemplate) -> None:
        from gatekeeper_tpu.ir.lower_cel import lower_cel_template

        self._cel.add_template(template)
        self._cel_kinds = self._cel_kinds | {template.kind}
        compiled = self._cel._templates[template.kind]
        program, err, _hit = self._lower_or_cached(
            template.kind, "cel", template,
            lambda: lower_cel_template(
                compiled, template.kind, self.vocab,
                schema_hint=template.parameters_schema,
            ))
        self._install_inline(template.kind, program, err, "cel")

    def _install_inline(self, kind: str, program, err, engine: str) -> None:
        """Install one inline compile result copy-on-write: the serving
        dicts are REPLACED, not mutated, so a batch that captured them
        mid-flight never sees a half-applied template change (the same
        contract the generation swap gives, at single-template grain)."""
        programs = dict(self._programs)
        errors = dict(self._lower_errors)
        if program is not None:
            programs[kind] = program
            errors.pop(kind, None)
        else:
            programs.pop(kind, None)
            errors[kind] = err
        self._programs = programs
        self._lower_errors = errors
        self._count_lowering(kind, engine, program is not None)
        self.plan_epoch += 1
        self._inv_cache.pop(kind, None)
        self._render_specs.pop(kind, None)

    def remove_template(self, template_kind: str) -> None:
        if self.gen_coord is not None:
            self.gen_coord.submit_remove(template_kind)
            return
        if template_kind in self._cel_kinds:
            self._cel.remove_template(template_kind)
            self._cel_kinds = self._cel_kinds - {template_kind}
        else:
            self._interp.remove_template(template_kind)
        programs = dict(self._programs)
        programs.pop(template_kind, None)
        errors = dict(self._lower_errors)
        errors.pop(template_kind, None)
        self._programs = programs  # copy-on-write (see _install_inline)
        self._lower_errors = errors
        self.plan_epoch += 1
        self._inv_cache.pop(template_kind, None)
        self._render_specs.pop(template_kind, None)

    def add_constraint(self, constraint: Constraint) -> None:
        if constraint.kind in self._cel_kinds:
            self._cel.add_constraint(constraint)
        else:
            if self.gen_coord is not None and \
                    constraint.kind not in self._interp._templates and \
                    self.gen_coord.is_staged(constraint.kind):
                # template staged but not yet swapped in: the constraint
                # is accepted now and starts matching at the swap
                return
            self._interp.add_constraint(constraint)

    def remove_constraint(self, constraint: Constraint) -> None:
        if constraint.kind in self._cel_kinds:
            self._cel.remove_constraint(constraint)
        else:
            self._interp.remove_constraint(constraint)

    def _bump_data(self, path) -> None:
        self._data_version += 1
        # namespace-scope paths name the object kind at [3]: scope writes
        # only dirty that kind's referential tables
        if (len(path) >= 4 and path[0] == "namespace"):
            self._data_kind_versions[path[3]] = self._data_version
        else:
            self._data_kind_versions.clear()  # unknown shape: dirty all

    def add_data(self, target: str, path: Sequence[str], data: Any) -> None:
        self._interp.add_data(target, path, data)
        self._bump_data(path)

    def remove_data(self, target: str, path: Sequence[str]) -> None:
        self._interp.remove_data(target, path)
        self._bump_data(path)

    def wipe_data(self) -> None:
        self._interp.wipe_data()
        self._data_version += 1
        self._data_kind_versions.clear()

    # --- referential (data.inventory) join tables ----------------------
    def inventory_cols(self, kind: str, programs=None):
        """(cols, exact) for a lowered referential template; ({}, True)
        when the program has no inventory joins.  Cached per data version;
        out-of-vocab sids are definite misses so vocab growth alone never
        invalidates (see InventoryUniqueJoin eval).  ``programs`` pins a
        captured generation (a batch mid-swap must read ITS programs,
        not the freshly-swapped dict)."""
        from gatekeeper_tpu.ir.program import build_inventory_tables

        from gatekeeper_tpu.ir import nodes as _N
        from gatekeeper_tpu.ir.program import expr_nodes

        prog = (programs if programs is not None
                else self._programs).get(kind)
        if prog is None:
            return {}, True
        inv_kinds = tuple(sorted({
            n.spec.kind for n in expr_nodes(prog.program)
            if isinstance(n, _N.InventoryUniqueJoin)}))
        if not inv_kinds:
            return {}, True
        # per-inventory-kind versions: unrelated data writes don't force a
        # rebuild; a cleared map (wipe / odd path) falls back to the global
        versions = tuple(
            self._data_kind_versions.get(k, self._data_version)
            if self._data_kind_versions else self._data_version
            for k in inv_kinds)
        cached = self._inv_cache.get(kind)
        if cached is not None and cached[0] == versions:
            return cached[1], cached[2]
        cols, exact = build_inventory_tables(
            prog.program, self._interp._data, self.vocab)
        self._inv_cache[kind] = (versions, cols, exact)
        return cols, exact

    def inventory_exact(self, kind: str, programs=None) -> bool:
        """False when the kind's referential tables can't represent the
        current inventory exactly (non-string join values): callers must
        route the kind through the interpreter for this data version."""
        return self.inventory_cols(kind, programs=programs)[1]

    # --- external-data join tables (extdata/lane.py) --------------------
    def _active_extdata(self):
        """The lane this driver joins through: an explicitly attached one
        (tests) or the process/context-active lane (--extdata-lane)."""
        lane = getattr(self, "extdata_lane", None)
        if lane is not None:
            return lane
        from gatekeeper_tpu.extdata import lane as lane_mod

        return lane_mod.active()

    def extdata_ready(self, kind: str, programs=None) -> bool:
        """True when the kind may ride the device grid w.r.t. external
        data: no external-data joins at all, or an active lane in a
        device-join mode (batched/differential) with extractable key
        columns.  perkey mode (the authoritative reference) and lane-less
        processes route external-data kinds through the interpreter —
        whose ``external_data`` builtin resolves per key."""
        prog = (programs if programs is not None
                else self._programs).get(kind)
        if prog is None:
            return True
        keymap, extractable = extdata_key_cols(prog.program)
        if not keymap and extractable:
            return True
        lane = self._active_extdata()
        return (extractable and lane is not None and lane.device_join())

    def extdata_cols(self, kind: str, batch, programs=None) -> tuple:
        """(cols, ready) — vocab-padded ``ext:`` join tables covering
        every key THIS batch's subject columns reference: per provider,
        the key strings dedupe across the whole batch off the flattened
        sid arrays, the lane bulk-fetches the misses (one transport call
        per max_keys_per_call chunk; warm columns make zero), and the
        resident column serves the arrays.  Value strings intern here —
        callers must build vocab-derived tables (pred matrices) AFTER
        this call."""
        prog = (programs if programs is not None
                else self._programs).get(kind)
        if prog is None:
            return {}, True
        keymap, extractable = extdata_key_cols(prog.program)
        if not keymap and extractable:
            return {}, True
        lane = self._active_extdata()
        if lane is None or not lane.device_join() or not extractable:
            return {}, False
        import numpy as _np

        requests: dict = {}
        for provider in sorted(keymap):
            sids: set = set()
            for spec in keymap[provider]:
                col = batch.scalars.get(spec)
                if col is None:
                    col = batch.raggeds.get(spec)
                if col is None:
                    continue  # column absent from this batch's schema
                s = col.sid[col.kind == K_STR]
                if s.size:
                    sids.update(int(x) for x in _np.unique(s) if x >= 0)
            requests[provider] = sorted(self.vocab.string(s) for s in sids)
        if len(requests) > 1:
            # per-provider concurrency: land every provider's misses in
            # one fan-out, then build tables from the warm columns (the
            # table build interns value strings and stays on this thread)
            lane.ensure_many(requests)
        cols: dict = {}
        for provider, keys in requests.items():
            cols.update(lane.tables_for(provider, keys, self.vocab))
        return cols, True

    def extdata_differential(self, target, kind, cons, reviews, grid,
                             mask, cfg) -> None:
        """``--extdata-lane=differential``: the device join's verdicts
        must match the exact interpreter (whose external_data builtin
        resolved through the same lane, per-key-cross-checked) on every
        live (constraint, review) mask cell."""
        from gatekeeper_tpu.extdata.lane import ExtDataDivergence

        for ci, con in enumerate(cons):
            for oi in np.nonzero(mask[ci, : len(reviews)])[0].tolist():
                ref = self._interp.query(target, [con], reviews[oi], cfg)
                want = bool(ref.results)
                got = bool(grid[ci, oi])
                if want != got:
                    r = reviews[oi]
                    raise ExtDataDivergence(
                        f"extdata differential: {kind}/{con.name} on "
                        f"{r.request.namespace}/{r.request.name}: "
                        f"device={got} interpreter={want}")

    def query(self, target, constraints, review, cfg=None) -> QueryResponse:
        cel_cons = [c for c in constraints if c.kind in self._cel_kinds]
        rego_cons = [c for c in constraints if c.kind not in self._cel_kinds]
        if not cel_cons:
            return self._interp.query(target, constraints, review, cfg)
        resp = self._cel.query(target, cel_cons, review, cfg)
        if rego_cons:
            r2 = self._interp.query(target, rego_cons, review, cfg)
            resp.results.extend(r2.results)
            resp.stats_entries.extend(r2.stats_entries)
            if r2.trace:
                resp.trace = (resp.trace + "\n" + r2.trace
                              if resp.trace else r2.trace)
        return resp

    # --- restricted-inventory hit rendering ------------------------------
    # Rendering a device-detected hit re-runs the interpreter; for
    # referential templates that naively rescans the WHOLE inventory per hit
    # (O(inventory) per render).  A lowered program only reaches inventory
    # through its InventoryUniqueJoin equality, so entries whose join value
    # differs from the review object's subject values cannot satisfy any
    # clause (either polarity) — the interpreter may run against just the
    # join-key-matching candidates, exactly.
    def render_query(self, target, constraint, review,
                     cfg=None) -> QueryResponse:
        """Interpreter query for message rendering of a device hit, with the
        inventory restricted to join candidates where provably safe."""
        if constraint.kind in self._cel_kinds:
            return self._cel.query(target, [constraint], review, cfg)
        specs = self._render_restrict_specs(constraint.kind)
        if not specs or not (self._interp._data or {}).get("inventory"):
            return self._interp.query(target, [constraint], review, cfg)
        obj = review.request.object or {}
        ns_tree: dict = {}
        cluster_tree: dict = {}
        for spec, col in specs:
            index = self._render_index(spec)
            for val in _col_values(obj, col):
                for ns, apiver, name, entry in index.get(val, ()):
                    if spec.scope == "cluster":
                        # cluster-scope root has no namespace level
                        # (target.go:60-66: ["cluster", GV, Kind, name])
                        cluster_tree.setdefault(apiver, {}).setdefault(
                            spec.kind, {})[name] = entry
                    else:
                        ns_tree.setdefault(ns, {}).setdefault(
                            apiver, {}).setdefault(
                                spec.kind, {})[name] = entry
        return self._interp.query(
            target, [constraint], review, cfg,
            data_override={"inventory": {"namespace": ns_tree,
                                         "cluster": cluster_tree}},
        )

    def _render_restrict_specs(self, kind):
        """List of (InvTableSpec, subject column) when every inventory
        access of the kind's program is a join with a plain column-read
        subject; None when restriction would be unsafe (or no program)."""
        if kind in self._render_specs:
            return self._render_specs[kind]
        from gatekeeper_tpu.ir import nodes as _N
        from gatekeeper_tpu.ir.program import expr_nodes

        prog = self._programs.get(kind)
        specs: Optional[list] = []
        if prog is None:
            specs = None
        else:
            for node in expr_nodes(prog.program):
                if not isinstance(node, _N.InventoryUniqueJoin):
                    continue
                if isinstance(node.subject, _N.FeatSid) and \
                        _col_restrictable(node.subject.col):
                    specs.append((node.spec, node.subject.col))
                else:
                    # transformed or review-level subject: the object walk
                    # can't reproduce it — render with the full inventory
                    specs = None
                    break
        self._render_specs[kind] = specs
        return specs

    def _render_index(self, spec):
        """value -> [(ns, apiver, name, obj)] for one InvTableSpec, cached
        per inventory-kind data version (mirrors inventory_cols: unrelated
        kinds' writes must not force an O(inventory) rebuild)."""
        import re as _re

        key = spec.key()
        version = (self._data_kind_versions.get(spec.kind,
                                                self._data_version)
                   if self._data_kind_versions else self._data_version)
        cached = self._render_idx.get(key)
        if cached is not None and cached[0] == version:
            return cached[1]
        index: dict = {}
        rx = _re.compile(spec.apiver_regex) if spec.apiver_regex else None
        inv = (self._interp._data or {}).get("inventory", {})
        if spec.scope == "cluster":
            # cluster root is {apiver: {Kind: {name: obj}}}: walk it as a
            # single pseudo-namespace (ns="" is never read back — the
            # cluster tree rebuild drops it)
            roots = [("", inv.get("cluster", {}) or {})]
        else:
            roots = list((inv.get("namespace", {}) or {}).items())
        for ns, by_apiver in roots:
            if not isinstance(by_apiver, dict):
                continue
            for apiver, by_kind in by_apiver.items():
                if rx is not None and not rx.search(str(apiver)):
                    continue
                if not isinstance(by_kind, dict):
                    continue
                objs = by_kind.get(spec.kind)
                if not isinstance(objs, dict):
                    continue
                for name, entry in objs.items():
                    for val in walk_join_values(entry, spec.join_path):
                        if isinstance(val, str):
                            index.setdefault(val, []).append(
                                (ns, apiver, name, entry))
        self._render_idx[key] = (version, index)
        return index

    def dump(self) -> dict:
        d = self._interp.dump()
        d["lowered"] = sorted(self._programs)
        d["fallback"] = dict(self._lower_errors)
        return d

    def get_description_for_stat(self, stat_name: str) -> str:
        return {
            "batchEvalNS": "nanoseconds spent in the device verdict kernel",
            "flattenNS": "nanoseconds spent flattening objects to columns",
        }.get(stat_name, self._interp.get_description_for_stat(stat_name))

    # --- the TPU path ----------------------------------------------------
    def lowered_kinds(self) -> list[str]:
        return sorted(self._programs)

    def fallback_kinds(self) -> dict[str, str]:
        return dict(self._lower_errors)

    def query_batch(
        self,
        target: str,
        constraints: Sequence[Constraint],
        reviews: Sequence[GkReview],
        cfg: Optional[ReviewCfg] = None,
        render_messages: bool = True,
    ) -> list[QueryResponse]:
        """Evaluate all constraints against all reviews in one device pass.

        Returns one QueryResponse per review.  This is the kernel behind the
        audit sweep (SURVEY.md §3.2) and the webhook batcher.
        """
        from gatekeeper_tpu.observability import costattr, tracing

        t0 = time.perf_counter()
        occ: dict = {}
        with tracing.span("device.query_batch", n=len(reviews),
                          constraints=len(constraints)):
            out = self._query_batch_impl(target, constraints, reviews,
                                         cfg, render_messages,
                                         occ_out=occ)
        attr = costattr.active()
        if attr is not None and occ:
            # the shared admission pass (flatten + grid + render) splits
            # across templates by mask row occupancy — per-template
            # shares sum back to this span's wall time
            attr.attribute(time.perf_counter() - t0,
                           {k: 1.0 + v for k, v in occ.items()},
                           costattr.EP_WEBHOOK, costattr.PHASE_DISPATCH,
                           rows=occ)
        return out

    def _query_batch_impl(self, target, constraints, reviews, cfg,
                          render_messages,
                          occ_out: Optional[dict] = None
                          ) -> list[QueryResponse]:
        cfg = cfg or ReviewCfg()
        n = len(reviews)
        responses = [QueryResponse() for _ in range(n)]
        if n == 0 or not constraints:
            return responses
        from gatekeeper_tpu.resilience.faults import fault_point

        fault_point("device.dispatch", lane="query_batch", n=n)

        objects = [r.request.object or {} for r in reviews]
        namespaces = [r.namespace for r in reviews]
        sources = [r.source for r in reviews]

        by_kind: dict[str, list[Constraint]] = {}
        for con in constraints:
            by_kind.setdefault(con.kind, []).append(con)

        # capture the generation ONCE: a swap replaces these objects (it
        # never mutates them), so this batch finishes on the generation
        # it started on even when templates churn mid-flight
        programs = self._programs
        cel_kinds = self._cel_kinds

        lowered_kinds = [k for k in by_kind
                         if k in programs
                         and self.inventory_exact(k, programs=programs)
                         and self.extdata_ready(k, programs=programs)]
        fallback_kinds = [k for k in by_kind if k not in lowered_kinds]

        t0 = time.perf_counter_ns()
        # DELETE reviews diverge for CEL kinds (object unset, anyObject =
        # oldObject — driver.go:184-186) while the flattened columns carry
        # the copied object: route those (constraint, review) pairs through
        # the CEL evaluator instead of the grid
        cel_delete_idx = [
            oi for oi, r in enumerate(reviews)
            if r.request.operation == "DELETE"
        ] if cel_kinds else []
        verdicts: dict[str, np.ndarray] = {}
        # flatten once with the union schema (identity columns always needed
        # for match masks, even when every kind falls back)
        if self.gen_coord is not None:
            # generation mode: the union is pinned to the GENERATION's
            # full program set (sorted — the same merge the pre-swap
            # warm performs), not to which kinds happen to have active
            # constraints this batch.  Constraint churn therefore never
            # reshapes the flatten (a removed constraint would otherwise
            # shrink the union and retrace every remaining kernel on the
            # serving thread); the union only moves at a swap, whose
            # shapes the background warm already traced.  Cached per
            # generation epoch — one merge per swap, not per batch.
            cached = self._qb_schema
            if cached is not None and cached[0] == self.plan_epoch:
                schema = cached[1]
            else:
                schema = Schema()
                for kind in sorted(programs):
                    schema.merge(programs[kind].program.schema)
                self._qb_schema = (self.plan_epoch, schema)
        else:
            schema = Schema()
            for kind in lowered_kinds:
                schema.merge(programs[kind].program.schema)
        # power-of-two padding above the base bucket caps the number of
        # distinct jit shapes at log2(max N): first-compile cost is bounded
        pad_n = self.batch_bucket
        while pad_n < n:
            pad_n *= 2
        tf = time.perf_counter_ns()
        flattener = Flattener(schema, self.vocab)
        review_docs = [
            {
                "kind": r.request.kind,
                "operation": r.request.operation,
                "name": r.request.name,
                "namespace": r.request.namespace,
                "userInfo": r.request.user_info,
                # UPDATE-delta policies (upstream noupdateserviceaccount)
                # compare object fields against oldObject fields; absent
                # outside UPDATE/DELETE, so such rules stay vacuous on
                # CREATE and in audit sweeps — same as the interpreter
                "oldObject": r.request.old_object,
            }
            for r in reviews
        ]
        batch = flattener.flatten(objects, pad_n=pad_n, reviews=review_docs)
        flatten_ns = time.perf_counter_ns() - tf
        if self.gen_coord is not None:
            # retain the latest real batch (references, not copies): the
            # pre-swap warm replays it through the next generation so
            # the warm traces land at the EXACT serving shapes (ragged
            # widths are data-dependent; a synthetic object can't
            # reproduce them)
            self._warm_ref = (objects, review_docs, pad_n)
        eval_ns = 0
        te = time.perf_counter_ns()
        batch_memo: dict = {}  # this batch's uploads, shared across kinds
        for kind in lowered_kinds:
            prog = programs[kind]
            cons = by_kind[kind]
            table = build_param_table(prog.program, cons, self.vocab)
            # extdata tables BEFORE run: the build interns value strings
            # the vocab tables inside run must cover
            ext_cols, _ext_ok = self.extdata_cols(kind, batch,
                                                  programs=programs)
            extra = self.inventory_cols(kind, programs=programs)[0]
            if ext_cols:
                extra = {**extra, **ext_cols}
            grid = prog.run(batch, table, vocab=self.vocab,
                            extra_cols=extra,
                            dev_cache=self._dev_cache,
                            batch_cache=batch_memo)
            mask = masks_mod.constraint_masks(
                cons, batch, self.vocab, objects, namespaces, sources
            )
            if occ_out is not None:
                occ_out[kind] = int(mask.sum())
            # the admission grid is host-folded (batches are <=64 wide;
            # per-request rendering needs every hit anyway) — account the
            # fetch so d2h pressure is visible next to the audit lane's
            self.perf["d2h_bytes"] = (self.perf.get("d2h_bytes", 0.0)
                                      + grid.nbytes)
            grid = grid[:, : batch.n] & mask
            if ext_cols:
                lane = self._active_extdata()
                if lane is not None and lane.mode == "differential":
                    self.extdata_differential(target, kind, cons, reviews,
                                              grid, mask, cfg)
            if kind in cel_kinds and cel_delete_idx:
                for ci, con in enumerate(cons):
                    for oi in cel_delete_idx:
                        if mask[ci, oi]:
                            qr = self._cel.query(target, [con], reviews[oi],
                                                 cfg)
                            responses[oi].results.extend(qr.results)
                    grid[ci, cel_delete_idx] = False
            verdicts[kind] = grid
        eval_ns = time.perf_counter_ns() - te

        # render hits through the exact engine
        for kind in lowered_kinds:
            cons = by_kind[kind]
            grid = verdicts[kind]
            for ci, con in enumerate(cons):
                hit_idx = np.nonzero(grid[ci, :n])[0]
                for oi in hit_idx.tolist():
                    if render_messages:
                        qr = self.render_query(
                            target, con, reviews[oi], cfg
                        )
                        responses[oi].results.extend(qr.results)
                        if qr.trace:
                            responses[oi].trace = (
                                (responses[oi].trace + "\n" + qr.trace)
                                if responses[oi].trace else qr.trace
                            )
                    else:
                        responses[oi].results.append(
                            Result(target=target, msg="", constraint=con.raw)
                        )

        # fallback kinds: exact engine on match-filtered pairs
        for kind in fallback_kinds:
            cons = by_kind[kind]
            engine = (self._cel.query if kind in cel_kinds
                      else self._interp.query)
            mask = masks_mod.constraint_masks(
                cons, batch, self.vocab, objects, namespaces, sources
            )
            if occ_out is not None:
                occ_out[kind] = int(mask[:, :n].sum())
            for ci, con in enumerate(cons):
                for oi in np.nonzero(mask[ci, :n])[0].tolist():
                    qr = engine(target, [con], reviews[oi], cfg)
                    responses[oi].results.extend(qr.results)

        if cfg.stats:
            total_ns = time.perf_counter_ns() - t0
            entry = StatsEntry(
                scope="batch",
                stats_for=f"{len(constraints)} constraints x {n} objects",
                stats=[
                    Stat("batchEvalNS", eval_ns,
                         {"type": "engine", "value": DRIVER_NAME}),
                    Stat("flattenNS", flatten_ns,
                         {"type": "engine", "value": DRIVER_NAME}),
                    Stat("totalNS", total_ns,
                         {"type": "engine", "value": DRIVER_NAME}),
                ],
            )
            responses[0].stats_entries.append(entry)
        return responses
