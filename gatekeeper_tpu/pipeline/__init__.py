"""Staged host-pipeline executor (the audit sweep's overlap plane).

See :mod:`gatekeeper_tpu.pipeline.executor` for the dataflow engine and
:func:`resolve_schedule` for the serial-fallback policy (one-core hosts
and ``--pipeline=off`` keep the eager-poll serial schedule).
"""

from gatekeeper_tpu.pipeline.executor import (  # noqa: F401
    PipelineError,
    PipelineRun,
    Stage,
    StagedPipeline,
    StageStats,
    effective_cpu_count,
)

PIPELINE_MODES = ("auto", "on", "off", "differential")


def resolve_schedule(mode: str, device_capable: bool,
                     cpu_count=None) -> str:
    """Pick the sweep schedule: 'serial', 'pipelined', or 'differential'.

    - ``off`` (or a non-device-capable evaluator) -> serial always.
    - ``auto`` -> pipelined only when the host has >1 effective core
      (the round-5 lesson: stage threads on a one-core host thrash the
      GIL and DOUBLE flatten wall time; the serial eager-poll schedule
      is strictly better there).
    - ``on`` -> pipelined regardless of core count (tests, experiments).
    - ``differential`` -> run BOTH schedules and assert bit-identical
      output (totals, kept order, rendered messages).
    """
    if mode not in PIPELINE_MODES:
        raise ValueError(
            f"pipeline mode {mode!r} not in {PIPELINE_MODES}")
    if not device_capable or mode == "off":
        return "serial"
    if mode == "auto":
        n = effective_cpu_count() if cpu_count is None else cpu_count
        return "pipelined" if n > 1 else "serial"
    if mode == "on":
        return "pipelined"
    return "differential"
