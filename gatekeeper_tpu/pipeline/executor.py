"""Staged host-pipeline executor: bounded queues, backpressure, stats.

The audit sweep's host phases (flatten / wire-pack / fold-render) dominate
wall-clock while the device is idle ~97% of a pass (VERDICT r4 weak #1-2).
This module is the generic fix: a linear dataflow of stages connected by
BOUNDED channels, each stage on its own thread(s), so chunk K's flatten
(GIL-released C columnizer) overlaps chunk K-1's collect/fold and the
device/wire waits hide behind host work — the tf.data-style overlapped
prefetch pattern of training-stack input pipelines, applied to a policy
sweep.

Design constraints, in order:

- **bit-identical output**: stage emission preserves source order even for
  multi-worker stages (a per-stage reorder buffer keyed by the input
  sequence number), so a pipelined sweep folds chunks in exactly the
  serial schedule's order.
- **backpressure, no deadlock**: every channel is bounded; a slow stage
  stalls its producers (at O(queue_cap) buffered chunks of host memory)
  instead of queueing unboundedly.  A stage failure aborts the whole
  pipeline — every blocked put/get wakes and unwinds, the first error
  re-raises on the caller thread.
- **instrumentation**: per-stage busy/wait/stall seconds, items, input
  queue depth high-water marks, and occupancy (busy / pipeline wall) —
  enough for a bench artifact to PROVE the overlap (sum of stage busy
  times exceeding the region's wall time).

One-core degradation (the round-5 lesson: a collector thread doubled
flatten wall-time on a one-core host — two GIL-hungry threads thrash):
callers consult :func:`effective_cpu_count` and keep the serial schedule
when the host cannot actually run stages in parallel.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional, Sequence


def effective_cpu_count() -> int:
    """Cores this process may actually use: the scheduling affinity mask
    when the platform exposes it (containers with cpuset limits report
    the limit, not the node size), else ``os.cpu_count()``."""
    getaff = getattr(os, "sched_getaffinity", None)
    if getaff is not None:
        try:
            return len(getaff(0)) or 1
        except OSError:
            pass
    return os.cpu_count() or 1


from gatekeeper_tpu.observability import tracing
from gatekeeper_tpu.resilience.faults import fault_point


def _log_stage_restart(stage: str, attempt: int, exc: BaseException) -> None:
    try:
        from gatekeeper_tpu.utils.logging import log_event

        log_event("warning", "pipeline stage worker restarted",
                  event_type="pipeline_worker_restart",
                  stage=stage, attempt=attempt, error=str(exc))
    except Exception:
        pass


class PipelineError(Exception):
    """A stage raised; carries the stage name, original error as __cause__."""

    def __init__(self, stage: str, cause: BaseException):
        super().__init__(f"pipeline stage '{stage}' failed: {cause!r}")
        self.stage = stage


class _Aborted(Exception):
    """Internal: a channel operation was interrupted by pipeline abort."""


_DONE = object()  # end-of-stream sentinel (bypasses channel capacity)
_SKIP = object()  # ordered-emit placeholder for dropped (None) results


class _Chan:
    """Bounded FIFO channel with depth high-water tracking and abort-aware
    blocking.  ``get`` also hands out a monotonically increasing arrival
    index — assigned atomically with the pop — which multi-worker stages
    use to restore input order on emission."""

    def __init__(self, cap: int, abort: threading.Event):
        self._q: deque = deque()
        self._cap = max(1, cap)
        self._abort = abort
        self._cond = threading.Condition()
        self._next_idx = 0
        self.highwater = 0

    def put(self, item) -> None:
        with self._cond:
            # the sentinel bypasses capacity: shutdown must never block
            while item is not _DONE and len(self._q) >= self._cap:
                if self._abort.is_set():
                    raise _Aborted()
                self._cond.wait(0.05)
            if self._abort.is_set():
                raise _Aborted()
            self._q.append(item)
            # the sentinel rides above capacity; don't let it inflate the
            # reported depth high-water
            if item is not _DONE and len(self._q) > self.highwater:
                self.highwater = len(self._q)
            self._cond.notify_all()

    def get(self) -> tuple:
        """-> (arrival_idx, item); idx is -1 for the _DONE sentinel."""
        with self._cond:
            while not self._q:
                if self._abort.is_set():
                    raise _Aborted()
                self._cond.wait(0.05)
            item = self._q.popleft()
            if item is _DONE:
                return -1, item
            idx = self._next_idx
            self._next_idx += 1
            self._cond.notify_all()
            return idx, item


@dataclass
class StageStats:
    """Per-stage timings (seconds) + queue telemetry for one pipeline run."""

    name: str
    workers: int = 1
    items: int = 0
    busy_s: float = 0.0   # inside fn (summed across workers)
    wait_s: float = 0.0   # blocked on upstream (input get)
    stall_s: float = 0.0  # blocked on downstream (output put, backpressure)
    queue_highwater: int = 0  # input channel depth high-water
    retries: int = 0  # crashed-worker restarts that re-ran an item

    def occupancy(self, wall_s: float) -> float:
        """Fraction of the pipeline wall this stage spent doing work
        (per worker-slot; 1.0 = the stage was the bottleneck)."""
        if wall_s <= 0:
            return 0.0
        return min(1.0, self.busy_s / (wall_s * max(1, self.workers)))


@dataclass
class PipelineRun:
    """Result of StagedPipeline.run: stats + wall clock."""

    wall_s: float = 0.0
    source_items: int = 0
    source_stall_s: float = 0.0  # source blocked on stage-1 backpressure
    stages: list = field(default_factory=list)  # [StageStats]

    def stage(self, name: str) -> Optional[StageStats]:
        for s in self.stages:
            if s.name == name:
                return s
        return None

    def stage_busy_sum(self) -> float:
        """Serial-equivalent host+device seconds: if this exceeds wall_s,
        the stages measurably overlapped."""
        return sum(s.busy_s for s in self.stages)

    def summary(self) -> dict:
        """JSON-ready per-stage breakdown (bench artifacts, metrics)."""
        return {
            "wall_s": round(self.wall_s, 3),
            "stage_busy_sum_s": round(self.stage_busy_sum(), 3),
            "overlap_ratio": round(
                self.stage_busy_sum() / self.wall_s, 3
            ) if self.wall_s > 0 else 0.0,
            "source_items": self.source_items,
            "source_stall_s": round(self.source_stall_s, 3),
            "stages": {
                s.name: {
                    "items": s.items,
                    "busy_s": round(s.busy_s, 3),
                    "wait_s": round(s.wait_s, 3),
                    "stall_s": round(s.stall_s, 3),
                    "occupancy": round(s.occupancy(self.wall_s), 3),
                    "queue_highwater": s.queue_highwater,
                    "workers": s.workers,
                    "retries": s.retries,
                }
                for s in self.stages
            },
        }


class Stage:
    """One pipeline stage: ``fn(item) -> item | None`` (None drops the
    item).  ``workers`` > 1 fans the stage over a thread pool; emission
    to the next stage is ALWAYS restored to input order, so downstream
    stages (and the final fold) observe the serial schedule's sequence.
    ``queue_cap`` bounds this stage's INPUT queue — the backpressure knob
    limiting how far its producer may run ahead."""

    def __init__(self, name: str, fn: Callable[[Any], Any],
                 workers: int = 1, queue_cap: int = 2,
                 max_retries: int = 0):
        if workers < 1:
            raise ValueError(f"stage {name}: workers must be >= 1")
        self.name = name
        self.fn = fn
        self.workers = workers
        self.queue_cap = queue_cap
        # crashed-worker policy (resilience layer): a worker whose fn
        # raises restarts and re-runs THE SAME item up to max_retries
        # times before the failure aborts the pipeline — no item is ever
        # silently dropped, and the chunk sequence downstream stages see
        # is unchanged (the reorder buffer keys on arrival index)
        self.max_retries = max_retries


class _OrderedEmit:
    """Reorder buffer at a stage's exit: results emit downstream in input
    arrival order regardless of worker completion order.  Bounded by the
    stage's worker count (a worker blocks in emit until its predecessors
    have emitted — via the downstream channel put, not a spin)."""

    def __init__(self, out: Optional[_Chan]):
        self._out = out
        self._lock = threading.Lock()       # guards _buf/_next
        self._emit_lock = threading.Lock()  # serializes downstream puts
        self._buf: dict = {}
        self._next = 0

    def emit(self, idx: int, item) -> float:
        """Returns seconds spent blocked on the downstream put."""
        stall = 0.0
        with self._lock:
            self._buf[idx] = item
        # drain under a dedicated emit mutex: claims advance _next one item
        # at a time IN ORDER and the put happens before the next claim, so
        # two workers finishing out of order can never interleave their
        # downstream puts.  Parking (above) stays lock-cheap — a sibling
        # blocked here never prevents others from parking results.
        with self._emit_lock:
            while True:
                with self._lock:
                    if self._next not in self._buf:
                        break
                    it = self._buf.pop(self._next)
                    self._next += 1
                if it is not _SKIP and self._out is not None:
                    t0 = time.perf_counter()
                    self._out.put(it)
                    stall += time.perf_counter() - t0
        return stall


class StagedPipeline:
    """A linear chain of stages fed from an iterable source.

    ``run(source)`` drives the source on the CALLING thread (listing
    stays where the caller's generator state lives), spawns stage
    workers, blocks until the last stage drains, and returns a
    :class:`PipelineRun`.  Any stage exception (or source exception)
    aborts every thread and re-raises."""

    def __init__(self, stages: Sequence[Stage], source_cap: int = 2):
        if not stages:
            raise ValueError("pipeline needs at least one stage")
        self.stages = list(stages)
        self.source_cap = source_cap

    def run(self, source: Iterable) -> PipelineRun:
        abort = threading.Event()
        run = PipelineRun()
        stats = [StageStats(name=s.name, workers=s.workers)
                 for s in self.stages]
        run.stages = stats
        chans = [_Chan(self.stages[0].queue_cap or self.source_cap, abort)]
        for s in self.stages[1:]:
            chans.append(_Chan(s.queue_cap, abort))
        chans.append(None)  # last stage has no output
        emits = [_OrderedEmit(chans[i + 1]) for i in range(len(self.stages))]

        first_error: list = []  # [(stage_name, exc)]
        err_lock = threading.Lock()

        def fail(stage_name: str, exc: BaseException) -> None:
            with err_lock:
                if not first_error:
                    first_error.append((stage_name, exc))
            abort.set()

        # per-stage countdown: the LAST worker to exit propagates _DONE
        remaining = [s.workers for s in self.stages]
        rem_lock = threading.Lock()

        # chunk-scoped span parent: stage workers run on their own
        # threads, so the caller's ambient span (e.g. the audit sweep
        # root) is captured HERE and passed explicitly — every
        # ``pipeline.stage.<name>`` span carries its chunk index, so one
        # slow chunk is visible on the timeline
        trace_parent = tracing.current_span()

        def worker(si: int, stage: Stage) -> None:
            st = stats[si]
            in_ch, out_ch = chans[si], chans[si + 1]
            try:
                while True:
                    t0 = time.perf_counter()
                    idx, item = in_ch.get()
                    wait = time.perf_counter() - t0
                    if item is _DONE:
                        in_ch.put(_DONE)  # release sibling workers
                        break
                    t0 = time.perf_counter()
                    attempt = 0
                    with tracing.span(f"pipeline.stage.{stage.name}",
                                      parent=trace_parent, chunk=idx) as sp:
                        while True:
                            try:
                                fault_point(f"pipeline.stage.{stage.name}")
                                out = stage.fn(item)
                                break
                            except _Aborted:
                                raise
                            except BaseException as e:  # noqa: BLE001
                                if attempt >= stage.max_retries or \
                                        abort.is_set():
                                    fail(stage.name, e)
                                    return
                                attempt += 1
                                with st_locks[si]:
                                    st.retries += 1
                                sp.add_event("stage_retry",
                                             attempt=attempt, error=str(e))
                                _log_stage_restart(stage.name, attempt, e)
                    busy = time.perf_counter() - t0
                    stall = emits[si].emit(
                        idx, _SKIP if out is None else out)
                    with st_locks[si]:
                        st.items += 1
                        st.busy_s += busy
                        st.wait_s += wait
                        st.stall_s += stall
            except _Aborted:
                return
            finally:
                last = False
                with rem_lock:
                    remaining[si] -= 1
                    last = remaining[si] == 0
                if last and out_ch is not None and not abort.is_set():
                    try:
                        out_ch.put(_DONE)
                    except _Aborted:
                        pass

        st_locks = [threading.Lock() for _ in self.stages]
        threads = []
        for si, stage in enumerate(self.stages):
            for w in range(stage.workers):
                t = threading.Thread(
                    target=worker, args=(si, stage), daemon=True,
                    name=f"pipe-{stage.name}-{w}")
                t.start()
                threads.append(t)

        t_start = time.perf_counter()
        try:
            for item in source:
                t0 = time.perf_counter()
                chans[0].put(item)
                run.source_stall_s += time.perf_counter() - t0
                run.source_items += 1
            chans[0].put(_DONE)
        except _Aborted:
            pass
        except BaseException as e:  # noqa: BLE001 — source failed
            fail("<source>", e)
        # wait for drain (or abort): the last stage's worker exit is the
        # completion signal; on abort, _Aborted unwinds every thread
        for t in threads:
            while t.is_alive():
                t.join(0.1)
                if abort.is_set():
                    t.join(5.0)
                    break
        run.wall_s = time.perf_counter() - t_start
        for si, ch in enumerate(chans[:-1]):
            stats[si].queue_highwater = ch.highwater
        if first_error:
            stage_name, exc = first_error[0]
            raise PipelineError(stage_name, exc) from exc
        return run
