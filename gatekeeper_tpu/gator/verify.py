"""gator verify: declarative policy test suites.

Reference: pkg/gator/verify — Suite{tests[{name, template, constraint,
expansion?, cases[{name, object, inventory[], assertions[]}]}]} with
go-test-style output.  Assertion semantics (assertion.go): ``violations`` is
"yes" (≥1), "no" (0) or an exact int, counted over violations whose message
matches the optional ``message`` regex; default "yes".
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.apis.constraints import GATOR_EP
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.expansion.expander import Expander
from gatekeeper_tpu.gator import reader
from gatekeeper_tpu.match.match import SOURCE_GENERATED, SOURCE_ORIGINAL
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file


class SuiteError(Exception):
    pass


@dataclass
class CaseResult:
    name: str
    error: str = ""
    skipped: bool = False
    duration_s: float = 0.0
    # rego print() output captured during this case (reference: the
    # PrintHook the verify runner wires into the driver, SURVEY §2.8)
    prints: list = field(default_factory=list)


@dataclass
class TestResult:
    name: str
    cases: list = field(default_factory=list)
    error: str = ""
    skipped: bool = False


@dataclass
class SuiteResult:
    path: str
    tests: list = field(default_factory=list)
    error: str = ""
    skipped: bool = False

    def failed(self) -> bool:
        if self.error:
            return True
        for t in self.tests:
            if t.error:
                return True
            for c in t.cases:
                if c.error:
                    return True
        return False


def is_suite(obj: dict) -> bool:
    return (obj.get("kind") == "Suite"
            and str(obj.get("apiVersion", "")).startswith(
                "test.gatekeeper.sh/"))


def find_suites(paths) -> list[str]:
    """Reference: read_suites.go:50 — walk dirs for Suite yaml files."""
    out = []
    for path in paths:
        if os.path.isdir(path):
            for root, _dirs, files in os.walk(path):
                for f in sorted(files):
                    if not f.endswith((".yaml", ".yml")):
                        continue
                    full = os.path.join(root, f)
                    try:
                        docs = load_yaml_file(full)
                    except Exception:
                        continue
                    if any(isinstance(d, dict) and is_suite(d)
                           for d in docs):
                        out.append(full)
        else:
            out.append(path)
    return out


def _assert_case(assertions, results) -> Optional[str]:
    """Returns error string or None (reference: assertion.go:38-130)."""
    if not assertions:
        assertions = [{}]
    for a in assertions:
        msg_re = a.get("message")
        try:
            pattern = re.compile(msg_re) if msg_re else None
        except re.error as e:
            return f"invalid message regex {msg_re!r}: {e}"
        matching = [r for r in results
                    if pattern is None or pattern.search(r.msg)]
        want = a.get("violations", "yes")
        got = len(matching)
        msgs = [r.msg for r in results]
        if isinstance(want, bool):  # YAML yes/no parse to bool
            want = "yes" if want else "no"
        if isinstance(want, int):
            if got != want:
                return (f"got {got} violations but want exactly {want}: "
                        f"messages {msgs}")
        elif want == "yes":
            if got == 0:
                return f"got 0 violations but want at least 1: messages {msgs}"
        elif want == "no":
            if got > 0:
                return f"got {got} violations but want none: messages {msgs}"
        else:
            return ('assertion.violations must be a nonnegative integer, '
                    '"yes", or "no"')
    return None


def run_suite(path: str, filter_re: Optional[str] = None) -> SuiteResult:
    sr = SuiteResult(path=path)
    docs = [d for d in load_yaml_file(path) if is_suite(d)]
    if not docs:
        sr.error = "no Suite found"
        return sr
    suite = docs[0]
    if suite.get("skip"):
        sr.skipped = True
        return sr
    base = os.path.dirname(os.path.abspath(path))
    pattern = re.compile(filter_re) if filter_re else None
    for test in suite.get("tests") or []:
        tr = TestResult(name=test.get("name", ""))
        sr.tests.append(tr)
        if test.get("skip"):
            tr.skipped = True
            continue
        if pattern and not pattern.search(tr.name):
            tr.skipped = True
            continue
        try:
            client, expander_objs = _build_test_client(test, base)
        except Exception as e:
            tr.error = str(e)
            continue
        for case in test.get("cases") or []:
            cr = CaseResult(name=case.get("name", ""))
            tr.cases.append(cr)
            if case.get("skip"):
                cr.skipped = True
                continue
            t0 = time.perf_counter()
            # capture rego print() output for this case only (the hook is
            # a contextvar: concurrent evaluation elsewhere is unaffected)
            from gatekeeper_tpu.lang.rego import builtins as _builtins

            tok = _builtins.set_print_hook(cr.prints.append)
            try:
                results = _run_case(client, case, base, expander_objs)
                err = _assert_case(case.get("assertions"), results)
                if err:
                    cr.error = err
            except Exception as e:
                cr.error = str(e)
            finally:
                _builtins.reset_print_hook(tok)
            cr.duration_s = time.perf_counter() - t0
    return sr


def _build_test_client(test: dict, base: str):
    template_path = test.get("template", "")
    if not template_path:
        raise SuiteError("test has no template")
    client = Client(
        target=K8sValidationTarget(),
        drivers=[RegoDriver(), CELDriver()],
        enforcement_points=[GATOR_EP],
    )
    template = load_yaml_file(os.path.join(base, template_path))[0]
    client.add_template(template)
    expander_objs = []
    constraint_path = test.get("constraint", "")
    if constraint_path:
        constraint = load_yaml_file(os.path.join(base, constraint_path))[0]
        client.add_constraint(constraint)
    expansion_path = test.get("expansion", "")
    if expansion_path:
        expander_objs.extend(load_yaml_file(os.path.join(base,
                                                         expansion_path)))
    return client, expander_objs


def _run_case(client: Client, case: dict, base: str, expander_objs):
    object_path = case.get("object", "")
    if not object_path:
        raise SuiteError("case has no object")
    objs = load_yaml_file(os.path.join(base, object_path))
    if not objs:
        raise SuiteError(f"no objects in {object_path}")
    under_test = objs[0]
    inventory = []
    for inv_path in case.get("inventory") or []:
        inventory.extend(load_yaml_file(os.path.join(base, inv_path)))
    for obj in inventory:
        client.add_data(obj)
    try:
        from gatekeeper_tpu.gator import reader

        if reader.is_admission_review(under_test):
            # AdmissionReview fixture: review the embedded request
            # (operation/oldObject/userInfo — the webhook's view) with
            # the namespace resolved from the fixture set; no expansion,
            # which operates on bare objects
            from gatekeeper_tpu.target.review import AugmentedReview
            from gatekeeper_tpu.webhook.policy import parse_admission_review

            req = parse_admission_review(under_test)
            expander = Expander([*inventory, *expander_objs])
            ns = expander.namespace_for(req.object or req.old_object or {})
            return client.review(
                AugmentedReview(admission_request=req, namespace=ns,
                                is_admission=True),
                enforcement_point=GATOR_EP,
            ).results()
        # namespaces resolved gator-style from object+inventory+expansion set
        expander = Expander([under_test, *inventory, *expander_objs])
        ns = expander.namespace_for(under_test)
        responses = client.review(
            AugmentedUnstructured(object=under_test, namespace=ns,
                                  source=SOURCE_ORIGINAL),
            enforcement_point=GATOR_EP,
        )
        for resultant in expander.expand(under_test):
            r_resp = client.review(
                AugmentedUnstructured(object=resultant.obj, namespace=ns,
                                      source=SOURCE_GENERATED),
                enforcement_point=GATOR_EP,
            )
            from gatekeeper_tpu.expansion import aggregate

            aggregate.override_enforcement_action(
                resultant.enforcement_action, r_resp)
            aggregate.aggregate_responses(resultant.template_name, responses,
                                          r_resp)
        return responses.results()
    finally:
        # per-case data must not leak to the next case, even on errors
        for obj in inventory:
            client.remove_data(obj)


def print_result(sr: SuiteResult, out=sys.stdout) -> None:
    """go-test-style output (reference: verify/printer.go)."""
    if sr.skipped:
        out.write(f"ok\t{sr.path}\t(skipped)\n")
        return
    if sr.error:
        out.write(f"FAIL\t{sr.path}\t{sr.error}\n")
        return
    for t in sr.tests:
        status = "SKIP" if t.skipped else ("FAIL" if t.error or any(
            c.error for c in t.cases) else "ok")
        out.write(f"=== RUN   {t.name}\n")
        if t.error:
            out.write(f"    error: {t.error}\n")
        for c in t.cases:
            if c.skipped:
                out.write(f"    --- SKIP: {t.name}/{c.name}\n")
                continue
            for line in getattr(c, "prints", []):
                # go-test idiom: print output interleaves above the verdict
                out.write(f"        print: {line}\n")
            if c.error:
                out.write(f"    --- FAIL: {t.name}/{c.name} "
                          f"({c.duration_s:.3f}s)\n        {c.error}\n")
            else:
                out.write(f"    --- PASS: {t.name}/{c.name} "
                          f"({c.duration_s:.3f}s)\n")
        out.write(f"--- {status}: {t.name}\n")
    out.write(("FAIL" if sr.failed() else "ok") + f"\t{sr.path}\n")


def run_cli(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator verify")
    p.add_argument("paths", nargs="*", default=["."])
    p.add_argument("--run", default=None,
                   help="regex filtering test names (like go test -run)")
    args = p.parse_args(argv)

    suites = find_suites(args.paths or ["."])
    if not suites:
        print("no test suites found", file=sys.stderr)
        return 1
    failed = False
    for s in suites:
        sr = run_suite(s, filter_re=args.run)
        print_result(sr)
        failed |= sr.failed()
    return 1 if failed else 0
