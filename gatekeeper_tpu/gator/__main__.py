from gatekeeper_tpu.gator.cli import main

import sys

sys.exit(main())
