"""gator test: in-memory full pipeline without a cluster.

Reference: pkg/gator/test/test.go:33-176 — build a client, add all templates,
then all constraints, then all objects as data; review every object (plus its
expansion resultants) at the gator enforcement point.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from gatekeeper_tpu.apis.constraints import GATOR_EP
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.types import Responses, Result
from gatekeeper_tpu.gator import reader
from gatekeeper_tpu.match.match import SOURCE_GENERATED, SOURCE_ORIGINAL
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import gvk_of


@dataclass
class GatorResult(Result):
    violating_object: Optional[dict] = None


@dataclass
class GatorResponse:
    target: str = ""
    results: list = field(default_factory=list)
    trace: Optional[str] = None


@dataclass
class GatorResponses:
    by_target: dict = field(default_factory=dict)
    stats_entries: list = field(default_factory=list)

    def results(self) -> list:
        out = []
        for target in sorted(self.by_target):
            resp = self.by_target[target]
            for r in resp.results:
                r.target = target
            out.extend(resp.results)
        return out


def _default_client(include_cel: bool = True, tracing: bool = False) -> Client:
    from gatekeeper_tpu.drivers.rego_driver import RegoDriver

    drivers: list[Any] = [RegoDriver(trace_enabled=tracing)]
    if include_cel:
        try:
            from gatekeeper_tpu.drivers.cel_driver import CELDriver

            drivers.append(CELDriver())
        except ImportError:
            pass
    return Client(
        target=K8sValidationTarget(),
        drivers=drivers,
        enforcement_points=[GATOR_EP],
    )


def test(
    objs: Sequence[dict],
    include_cel: bool = True,
    tracing: bool = False,
    stats: bool = False,
    client: Optional[Client] = None,
) -> GatorResponses:
    """Run the full offline pipeline (reference: gator/test.Test)."""
    client = client or _default_client(include_cel=include_cel, tracing=tracing)

    for obj in objs:
        if reader.is_template(obj):
            client.add_template(obj)
    for obj in objs:
        if reader.is_constraint(obj):
            client.add_constraint(obj)
    for obj in objs:
        if not reader.is_admission_review(obj):
            client.add_data(obj)

    from gatekeeper_tpu.expansion.expander import Expander

    expander = Expander(objs)

    responses = GatorResponses()

    def fold_review(review, obj):
        """Fold one client review into the aggregate response set — the
        single copy shared by the bare-object and AdmissionReview
        paths (results, traces, stats)."""
        for target_name, resp in review.by_target.items():
            t_resp = responses.by_target.setdefault(
                target_name, GatorResponse(target=target_name)
            )
            for r in resp.results:
                t_resp.results.append(
                    GatorResult(
                        target=r.target,
                        msg=r.msg,
                        constraint=r.constraint,
                        metadata=r.metadata,
                        enforcement_action=r.enforcement_action,
                        scoped_enforcement_actions=r.scoped_enforcement_actions,
                        violating_object=obj,
                    )
                )
            if resp.trace:
                t_resp.trace = (
                    (t_resp.trace + "\n\n" + resp.trace) if t_resp.trace
                    else resp.trace
                )
        responses.stats_entries.extend(review.stats_entries)

    from gatekeeper_tpu.expansion import aggregate

    def review_resultants(source_obj, ns, review):
        """Expand a (bare or request-embedded) object and aggregate its
        resultants' reviews — the reference expands EVERY reviewed object
        (test.go:125), including ones arriving inside AdmissionReview
        fixtures."""
        for resultant in expander.expand(source_obj):
            r_au = AugmentedUnstructured(
                object=resultant.obj, namespace=ns, source=SOURCE_GENERATED
            )
            r_review = client.review(
                r_au, enforcement_point=GATOR_EP, tracing=tracing,
                stats=stats
            )
            aggregate.override_enforcement_action(
                resultant.enforcement_action, r_review
            )
            aggregate.aggregate_responses(
                resultant.template_name, review, r_review
            )

    for obj in objs:
        if reader.is_admission_review(obj):
            # review the embedded AdmissionRequest (operation, oldObject,
            # userInfo — the webhook's view), with the namespace resolved
            # from the fixture set exactly like the bare-object path;
            # the embedded object then expands like any other (implied
            # workload resultants reviewed as Source=Generated)
            from gatekeeper_tpu.target.review import AugmentedReview
            from gatekeeper_tpu.webhook.policy import parse_admission_review

            req = parse_admission_review(obj)
            ns = expander.namespace_for(req.object or req.old_object or {})
            # snapshot BEFORE the review: the DELETE contract copies
            # oldObject into request.object in place (target.go:269-287
            # analog) and deleted objects must not expand; the deepcopy
            # also keeps the expander's in-place base mutation off the
            # fixture's request body
            import copy

            to_expand = copy.deepcopy(req.object) if req.object else None
            review = client.review(
                AugmentedReview(admission_request=req, namespace=ns,
                                is_admission=True),
                enforcement_point=GATOR_EP, tracing=tracing, stats=stats)
            if to_expand is not None:
                review_resultants(to_expand, ns, review)
            fold_review(review, obj)
            continue
        ns = expander.namespace_for(obj)
        au = AugmentedUnstructured(object=obj, namespace=ns,
                                   source=SOURCE_ORIGINAL)
        review = client.review(
            au, enforcement_point=GATOR_EP, tracing=tracing, stats=stats
        )
        review_resultants(obj, ns, review)
        fold_review(review, obj)
    return responses
