"""Input readers for the gator CLI (reference: pkg/gator/reader)."""

from __future__ import annotations

import os
import sys
from typing import Iterable

from gatekeeper_tpu.apis.constraints import CONSTRAINTS_GROUP
from gatekeeper_tpu.utils.unstructured import gvk_of, load_yaml_objects

YAML_EXTS = (".yaml", ".yml")
JSON_EXTS = (".json",)


def is_template(obj: dict) -> bool:
    group, _, kind = gvk_of(obj)
    return kind == "ConstraintTemplate" and group == "templates.gatekeeper.sh"


def is_constraint(obj: dict) -> bool:
    group, _, _ = gvk_of(obj)
    return group == CONSTRAINTS_GROUP


def is_expansion_template(obj: dict) -> bool:
    group, _, kind = gvk_of(obj)
    return kind == "ExpansionTemplate" and group == "expansion.gatekeeper.sh"


def is_admission_review(obj: dict) -> bool:
    """AdmissionReview fixture objects review the embedded request
    (operation/oldObject/userInfo intact) instead of a bare object —
    how upstream gator exercises UPDATE/DELETE-delta policies
    (reference: pkg/gator/reader read paths)."""
    group, _, kind = gvk_of(obj)
    return kind == "AdmissionReview" and group == "admission.k8s.io"


def read_sources(
    filenames: Iterable[str] = (), images: Iterable[str] = (), use_stdin: bool = False
) -> list[dict]:
    """Gather unstructured objects from files/dirs/stdin
    (reference: cmd/gator/test reader.ReadSources)."""
    objs: list[dict] = []
    for fname in filenames:
        if os.path.isdir(fname):
            for root, _dirs, files in os.walk(fname):
                for f in sorted(files):
                    if f.endswith(YAML_EXTS) or f.endswith(JSON_EXTS):
                        objs.extend(_read_file(os.path.join(root, f)))
        else:
            objs.extend(_read_file(fname))
    if use_stdin:
        objs.extend(load_yaml_objects(sys.stdin.read()))
    return objs


def _read_file(path: str) -> list[dict]:
    with open(path) as f:
        text = f.read()
    if path.endswith(JSON_EXTS):
        import json

        doc = json.loads(text)
        return doc if isinstance(doc, list) else [doc]
    return load_yaml_objects(text)
