"""``gator replay``: the offline policy time machine.

Replays a recorded decision corpus against a CANDIDATE template
library and prints the verdict diff — the "what would the candidate
have decided about last week's admissions" answer.  Two corpus
sources:

- ``-f sink.jsonl``: a capture-mode flight-recorder sink
  (``--flight-recorder-capture``), replayed through the webhook decide
  path, chunked and batched device-side;
- ``--from-spill DIR``: a ``--snapshot-spill`` directory (the
  state-at-rv spill), whose resident objects replay at the audit
  enforcement point against the spilled verdict store.

``--differential`` points ``--candidate`` at the RECORDED library and
asserts bit-identity to the record (exit 1 on any mismatch) — the
replay path validating itself.

    gator replay -f decisions.jsonl --candidate candidate/ \
        --compile-cache /var/cache/gk -o json
    gator replay --from-spill /var/spill --candidate candidate/
    gator replay -f decisions.jsonl --candidate recorded/ --differential
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt_table(report: dict) -> str:
    lines = []
    skipped = report.get("skipped") or {}
    if "records" in report:
        lines.append(f"replayed {report['records']} recorded decisions "
                     f"in {report.get('wall_s', 0)}s "
                     f"({report.get('decisions_per_s') or 0}/s)")
        rec, cand = report.get("recorded", {}), report.get("candidate", {})
        lines.append(f"  recorded:  allow={rec.get('allow', 0)} "
                     f"deny={rec.get('deny', 0)}")
        lines.append(f"  candidate: allow={cand.get('allow', 0)} "
                     f"deny={cand.get('deny', 0)} "
                     f"error={cand.get('error', 0)}")
        lines.append(f"  newly denied: {report['newly_denied']}   "
                     f"newly allowed: {report['newly_allowed']}   "
                     f"message changed: {report['message_changed']}")
    else:
        lines.append(f"replayed {report['rows']} spilled rows in "
                     f"{report.get('wall_s', 0)}s "
                     f"({report.get('decisions_per_s') or 0}/s)")
        lines.append(f"  divergent rows: {report['divergences_total']}")
    if skipped:
        drops = {k: v for k, v in skipped.items()
                 if k not in ("lines", "replayed") and v}
        if drops:
            lines.append("  skipped: " + "  ".join(
                f"{k}={v}" for k, v in sorted(drops.items())))
    by_con = report.get("by_constraint") or {}
    if by_con:
        lines.append("per-constraint divergence:")
        for name, entry in sorted(by_con.items()):
            lines.append("  " + name + ": " + "  ".join(
                f"{k}={v}" for k, v in sorted(entry.items()) if v))
    off = report.get("top_offenders") or {}
    for axis in ("namespace", "kind"):
        top = [t for t in off.get(axis, []) if t[0]]
        if top:
            lines.append(f"top offenders by {axis}: " + ", ".join(
                f"{n or '(cluster)'}={c}" for n, c in top[:5]))
    for d in report.get("divergences", [])[:10]:
        where = d.get("namespace", "")
        what = d.get("obj_kind", "")
        lines.append(f"  {d['kind']}: {what} {where}/{d.get('name', '')}"
                     + (f" [{d['constraint']}]" if "constraint" in d
                        else "")
                     + (f" uid={d['uid']}" if d.get("uid") else ""))
    low = report.get("lowering") or {}
    cc = report.get("compile_cache") or {}
    if low or cc:
        lines.append(f"candidate: {low.get('lowered', 0)}/"
                     f"{low.get('templates', 0)} templates lowered, "
                     f"compile cache hits={cc.get('hits', 0)} "
                     f"misses={cc.get('misses', 0)}")
    for err in report.get("candidate_load_errors", []):
        lines.append(f"  candidate load error: {err}")
    diff = report.get("differential")
    if diff:
        if diff["bit_identical"]:
            lines.append(f"differential: bit-identical over "
                         f"{diff['checked']} records")
        else:
            lines.append(f"differential: {diff['mismatches_total']} "
                         f"MISMATCHES over {diff['checked']} records")
            for m in diff["mismatches"][:10]:
                lines.append(f"  mismatch: {json.dumps(m, default=str)}")
    return "\n".join(lines)


def run_cli(argv: list) -> int:
    p = argparse.ArgumentParser(
        prog="gator replay",
        description="replay a recorded decision corpus (capture-mode "
                    "flight-recorder JSONL or a --snapshot-spill dir) "
                    "against a candidate template library and diff "
                    "the verdicts")
    p.add_argument("--filename", "-f", default="",
                   help="flight-recorder JSONL sink recorded with "
                        "--flight-recorder-capture")
    p.add_argument("--from-spill", default="",
                   help="a --snapshot-spill directory: replay its "
                        "resident objects against the spilled verdicts")
    p.add_argument("--candidate", action="append", default=[],
                   help="candidate library file/dir (repeatable): "
                        "templates + constraints + cluster fixtures "
                        "(v1 Namespaces resolve namespace selectors)")
    p.add_argument("--namespaces-from-spill", default="",
                   metavar="DIR",
                   help="take v1/Namespace fixtures from this "
                        "--snapshot-spill directory (the RECORDED "
                        "cluster's labels) instead of the candidate "
                        "docs — pins namespace-selector fidelity; "
                        "point it at the --from-spill dir to reuse it")
    p.add_argument("--differential", action="store_true",
                   help="candidate IS the recorded library: assert "
                        "bit-identity to the record (exit 1 on any "
                        "mismatch)")
    p.add_argument("--compile-cache", default="",
                   help="shared on-disk compile cache dir; warm = the "
                        "candidate loads with zero fresh lowerings")
    p.add_argument("--chunk", type=int, default=256,
                   help="decisions per batched device pass")
    p.add_argument("--limit", type=int, default=0,
                   help="replay at most N records (0 = all)")
    p.add_argument("--max-divergences", type=int, default=50,
                   help="row-level divergences listed in the report")
    p.add_argument("--max-message", type=int, default=512,
                   help="recorder message truncation (must match the "
                        "recording side for --differential)")
    p.add_argument("--output", "-o", default="",
                   choices=["", "json", "table"],
                   help="output format (default: human table)")
    args = p.parse_args(argv)

    if bool(args.filename) == bool(args.from_spill):
        print("error: exactly one of -f/--filename or --from-spill",
              file=sys.stderr)
        return 2
    if not args.candidate:
        print("error: --candidate is required (for --differential, "
              "point it at the recorded library)", file=sys.stderr)
        return 2

    from gatekeeper_tpu.gator import reader
    from gatekeeper_tpu.replay import core

    try:
        docs = reader.read_sources(args.candidate)
    except OSError as e:
        print(f"error: reading candidate: {e}", file=sys.stderr)
        return 1
    if not docs:
        print("error: no candidate docs found", file=sys.stderr)
        return 1
    ns_override = None
    if args.namespaces_from_spill:
        try:
            ns_override = core.namespaces_from_spill(
                core.read_spill(args.namespaces_from_spill))
        except (OSError, ValueError) as e:
            print(f"error: reading namespace spill: {e}",
                  file=sys.stderr)
            return 1
    runtime = core.load_candidate(docs,
                                  compile_cache_dir=args.compile_cache,
                                  namespaces=ns_override)
    try:
        if args.filename:
            records, counts = core.read_corpus(args.filename,
                                               limit=args.limit)
            report = core.replay_decisions(
                records, runtime, chunk=args.chunk,
                max_message=args.max_message,
                differential=args.differential,
                max_divergences=args.max_divergences,
                skipped=counts)
        else:
            spill = core.read_spill(args.from_spill)
            report = core.replay_spill(
                spill, runtime, chunk=args.chunk,
                differential=args.differential,
                max_divergences=args.max_divergences)
    except (OSError, ValueError) as e:
        print(f"error: reading corpus: {e}", file=sys.stderr)
        return 1

    if args.output == "json":
        print(json.dumps(report, indent=2, default=str))
    else:
        print(_fmt_table(report))
    diff = report.get("differential")
    if diff is not None and not diff["bit_identical"]:
        return 1
    return 0
