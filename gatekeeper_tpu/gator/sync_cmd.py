"""gator sync test: verify referential-data requirements are covered.

Reference: pkg/gator/sync — templates declare the GVKs their policies read
from ``data.inventory`` via the ``metadata.gatekeeper.sh/requires-sync-data``
annotation (a JSON list of requirement lists: ANY-of groups of
{groups, versions, kinds} ALL-of clauses); SyncSets and the Config resource
declare what is synced; the command reports requirements no sync source
covers.
"""

from __future__ import annotations

import argparse
import json
import sys

from gatekeeper_tpu.gator import reader
from gatekeeper_tpu.utils.unstructured import deep_get, gvk_of

REQUIRES_SYNC_ANNOTATION = "metadata.gatekeeper.sh/requires-sync-data"


def sync_sources(objs) -> list[dict]:
    """GVK entries synced by SyncSet CRs + the Config resource."""
    out = []
    for obj in objs:
        group, _, kind = gvk_of(obj)
        if kind == "SyncSet" and group == "syncset.gatekeeper.sh":
            out.extend(deep_get(obj, ("spec", "gvks"), []) or [])
        elif kind == "Config" and group == "config.gatekeeper.sh":
            for entry in deep_get(obj, ("spec", "sync", "syncOnly"), []) or []:
                out.append(entry)
    return out


def _covers(synced: dict, req: dict) -> bool:
    def any_match(want, got) -> bool:
        if not want:
            return True
        return got in want or "*" in want

    return (
        any_match(req.get("groups"), synced.get("group", ""))
        and any_match(req.get("versions"), synced.get("version", ""))
        and any_match(req.get("kinds"), synced.get("kind", ""))
    )


def missing_requirements(objs) -> dict:
    """template name -> list of uncovered requirement clauses."""
    synced = sync_sources(objs)
    out = {}
    for obj in objs:
        if not reader.is_template(obj):
            continue
        ann = deep_get(obj, ("metadata", "annotations"), {}) or {}
        raw = ann.get(REQUIRES_SYNC_ANNOTATION)
        if not raw:
            continue
        try:
            requirements = json.loads(raw)
        except json.JSONDecodeError as e:
            out[deep_get(obj, ("metadata", "name"), "?")] = [
                f"invalid {REQUIRES_SYNC_ANNOTATION} annotation: {e}"
            ]
            continue
        uncovered = []
        for any_of in requirements:
            if not isinstance(any_of, list):
                any_of = [any_of]
            ok = any(
                any(_covers(s, clause) for s in synced)
                for clause in any_of
            )
            if not ok:
                uncovered.append(any_of)
        if uncovered:
            out[deep_get(obj, ("metadata", "name"), "?")] = uncovered
    return out


def run_cli(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator sync test")
    # accept both `gator sync test -f ...` and `gator sync -f ...`
    if argv and argv[0] == "test":
        argv = argv[1:]
    p.add_argument("--filename", "-f", action="append", default=[])
    args = p.parse_args(argv)

    try:
        objs = reader.read_sources(args.filename, use_stdin=not args.filename)
    except OSError as e:
        print(f"error: reading: {e}", file=sys.stderr)
        return 1
    if not objs:
        print("no input data identified", file=sys.stderr)
        return 1
    missing = missing_requirements(objs)
    if not missing:
        print("all requirements satisfied")
        return 0
    for name, reqs in sorted(missing.items()):
        print(f"template {name} has unsatisfied sync requirements:")
        for r in reqs:
            print(f"  {json.dumps(r)}")
    return 1
