"""gator bench: policy evaluation benchmark harness.

Reference: pkg/gator/bench/bench.go — per-engine setup-vs-eval timing with
warmup, P50/P90/P99 latencies, reviews/sec (>=1000 iterations recommended
for P99 validity, bench.go:29-31).  Engines: rego | cel | all — plus two
TPU-native additions: ``tpu`` drives the batched verdict-grid path
(query_batch) instead of the per-review loop, and ``sweep`` drives the
full audit-sweep lane (AuditManager + ShardedEvaluator) through the
staged host pipeline (``--pipeline``), reporting the per-stage breakdown.
Both device engines report the lowering fallback fraction — templates
silently losing the device speedup are visible here.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from dataclasses import dataclass, field

from gatekeeper_tpu.apis.constraints import GATOR_EP
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.rego_driver import RegoDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.gator import reader
from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
from gatekeeper_tpu.target.review import AugmentedUnstructured
from gatekeeper_tpu.target.target import K8sValidationTarget


@dataclass
class BenchResult:
    engine: str
    iterations: int
    objects: int
    setup_client_s: float = 0.0
    setup_templates_s: float = 0.0
    setup_constraints_s: float = 0.0
    setup_data_s: float = 0.0
    total_eval_s: float = 0.0
    reviews_per_sec: float = 0.0
    p50_ms: float = 0.0
    p90_ms: float = 0.0
    p99_ms: float = 0.0
    violations: int = 0
    # device engines only (tpu/sweep): lowering coverage + the sweep
    # engine's per-stage pipeline breakdown (None for rego/cel/all)
    lowering: dict = None
    pipeline: dict = None

    def to_dict(self) -> dict:
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self.__dict__.items() if v is not None}


def _drivers_for(engine: str, compile_cache: str = ""):
    if engine == "rego":
        return [RegoDriver()]
    if engine == "cel":
        return [CELDriver()]
    if engine in ("tpu", "sweep"):
        cache = None
        if compile_cache:
            from gatekeeper_tpu.drivers.generation import CompileCache

            cache = CompileCache(compile_cache)
        return [TpuDriver(cel_driver=CELDriver(), compile_cache=cache)]
    return [RegoDriver(), CELDriver()]  # all


def run_bench(objs, engine: str, iterations: int,
              pipeline: str = "auto",
              flatten_lane: str = "auto",
              collect: str = "reduced",
              compile_cache: str = "",
              flatten_workers: int = 0,
              shard_chunks: int = 0) -> BenchResult:
    templates = [o for o in objs if reader.is_template(o)]
    constraints = [o for o in objs if reader.is_constraint(o)]
    data = [o for o in objs
            if not reader.is_template(o) and not reader.is_constraint(o)]
    r = BenchResult(engine=engine, iterations=iterations, objects=len(data))

    if engine == "mutate":
        return _run_mutate_bench(r, data, iterations)

    t0 = time.perf_counter()
    client = Client(target=K8sValidationTarget(),
                    drivers=_drivers_for(engine, compile_cache),
                    enforcement_points=[GATOR_EP])
    r.setup_client_s = time.perf_counter() - t0

    from gatekeeper_tpu.apis.templates import TemplateError
    from gatekeeper_tpu.utils.unstructured import deep_get

    skipped_kinds = set()
    t0 = time.perf_counter()
    for t in templates:
        try:
            client.add_template(t)
        except TemplateError:
            # template has no source for this engine (e.g. rego-only template
            # under --engine cel): skip it and its constraints
            skipped_kinds.add(deep_get(
                t, ("spec", "crd", "spec", "names", "kind"), ""))
    r.setup_templates_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for c in constraints:
        if c.get("kind") in skipped_kinds:
            continue
        client.add_constraint(c)
    r.setup_constraints_s = time.perf_counter() - t0
    from gatekeeper_tpu.gator import reader as _reader

    t0 = time.perf_counter()
    for d in data:
        if not _reader.is_admission_review(d):
            client.add_data(d)
    r.setup_data_s = time.perf_counter() - t0

    if engine == "sweep":
        return _run_sweep_bench(r, client, data, iterations, pipeline,
                                flatten_lane, collect, flatten_workers,
                                shard_chunks)

    from gatekeeper_tpu.target.review import AugmentedReview
    from gatekeeper_tpu.webhook.policy import parse_admission_review

    reviews = [
        (AugmentedReview(admission_request=parse_admission_review(o),
                         is_admission=True)
         if _reader.is_admission_review(o)
         else AugmentedUnstructured(object=o, source=SOURCE_ORIGINAL))
        for o in data
    ]
    latencies = []
    violations = 0
    from gatekeeper_tpu.observability import tracing

    if not reviews:
        total_reviews = 0
    elif engine == "tpu":
        # batched lane: one latency sample per batch pass over all objects
        client.review_batch(reviews, enforcement_point=GATOR_EP)  # warmup
        t_all0 = time.perf_counter()
        for _ in range(iterations):
            with tracing.span("gator.bench.pass", engine=engine,
                              n=len(reviews)):
                t0 = time.perf_counter()
                out = client.review_batch(reviews,
                                          enforcement_point=GATOR_EP)
                latencies.append((time.perf_counter() - t0) * 1000)
            violations = sum(
                len(o.results()) for o in out
                if not isinstance(o, Exception)
            )
        r.total_eval_s = time.perf_counter() - t_all0
        total_reviews = iterations * len(reviews)
    else:
        for rv in reviews:  # warmup pass (bench.go warmup)
            client.review(rv, enforcement_point=GATOR_EP)
        t_all0 = time.perf_counter()
        for _ in range(iterations):
            pass_violations = 0
            # one span per PASS, not per review: tracing must not tax the
            # per-review latency samples it sits next to
            with tracing.span("gator.bench.pass", engine=engine,
                              n=len(reviews)):
                for rv in reviews:
                    t0 = time.perf_counter()
                    resp = client.review(rv, enforcement_point=GATOR_EP)
                    latencies.append((time.perf_counter() - t0) * 1000)
                    pass_violations += len(resp.results())
            violations = pass_violations
        r.total_eval_s = time.perf_counter() - t_all0
        total_reviews = iterations * len(reviews)

    r.reviews_per_sec = (total_reviews / r.total_eval_s
                         if r.total_eval_s else 0.0)
    _fill_latencies(r, latencies)
    r.violations = violations
    if engine == "tpu":
        tpu = next((d for d in client.drivers
                    if hasattr(d, "lowering_stats")), None)
        if tpu is not None:
            r.lowering = tpu.lowering_stats()
    return r


def _run_mutate_bench(r: BenchResult, data: list,
                      iterations: int) -> BenchResult:
    """The ``mutate`` engine: a mutate burst through the batched lane
    (mutlane/lane.py) vs the per-object host fixed-point loop, over the
    input's mutators + objects.  ``reviews_per_sec`` is the batched
    lane's throughput; the host loop's lands in ``lowering`` alongside
    the lane breakdown (speedup = the headline)."""
    import copy

    from gatekeeper_tpu.mutation.mutators import (MUTATIONS_GROUP,
                                                  MUTATOR_KINDS)
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane import MutationLane
    from gatekeeper_tpu.observability import tracing
    from gatekeeper_tpu.utils.unstructured import gvk_of

    mutators, objects = [], []
    for o in data:
        group, _, kind = gvk_of(o)
        if group == MUTATIONS_GROUP and kind in MUTATOR_KINDS:
            mutators.append(o)
        elif kind not in ("ExpansionTemplate",):
            objects.append(o)
    if not mutators:
        raise ValueError("--engine mutate needs mutators in the input")
    if not objects:
        raise ValueError("--engine mutate needs objects in the input")
    t0 = time.perf_counter()
    system = MutationSystem()
    for m in mutators:
        system.upsert_unstructured(m)
    lane = MutationLane(system)
    lane.mutate_objects(objects[:1])  # compile warmup
    r.setup_client_s = time.perf_counter() - t0
    r.objects = len(objects)

    latencies: list = []
    lanes: dict = {}
    patch_ops = 0
    t_all0 = time.perf_counter()
    for _ in range(iterations):
        with tracing.span("gator.bench.pass", engine="mutate",
                          n=len(objects)):
            t0 = time.perf_counter()
            outcomes = lane.mutate_objects(objects)
            latencies.append((time.perf_counter() - t0) * 1000)
        lanes = {}
        patch_ops = 0
        for o in outcomes:
            lanes[o.lane] = lanes.get(o.lane, 0) + 1
            patch_ops += len(o.patch or ())
    r.total_eval_s = time.perf_counter() - t_all0
    r.reviews_per_sec = (iterations * len(objects) / r.total_eval_s
                         if r.total_eval_s else 0.0)
    _fill_latencies(r, latencies)
    r.violations = patch_ops  # for mutate: emitted patch ops, last pass

    # the host loop reference: the same burst through the per-object
    # fixed point (one pass is enough for the comparison number)
    t0 = time.perf_counter()
    for obj in objects:
        try:
            system.mutate(copy.deepcopy(obj))
        except Exception:
            pass  # error outcomes count as work done too
    host_s = time.perf_counter() - t0
    host_ops = len(objects) / host_s if host_s else 0.0
    r.lowering = {
        "lanes": lanes,
        "host_objs_per_sec": round(host_ops, 1),
        "batched_objs_per_sec": round(r.reviews_per_sec, 1),
        "speedup": round(r.reviews_per_sec / host_ops, 2)
        if host_ops else 0.0,
        "lowered_mutators": len(lane.compiled().lowered),
        "host_only_mutators": len(lane.compiled().host_only),
    }
    return r


def _fill_latencies(r: BenchResult, latencies: list) -> None:
    if latencies:
        qs = statistics.quantiles(latencies, n=100, method="inclusive") if (
            len(latencies) > 1) else [latencies[0]] * 99
        r.p50_ms, r.p90_ms, r.p99_ms = qs[49], qs[89], qs[98]


def _run_sweep_bench(r: BenchResult, client: Client, data: list,
                     iterations: int, pipeline: str,
                     flatten_lane: str = "auto",
                     collect: str = "reduced",
                     flatten_workers: int = 0,
                     shard_chunks: int = 0) -> BenchResult:
    """The ``sweep`` engine: the production audit lane (AuditManager +
    ShardedEvaluator) over the fixture's data objects, scheduled through
    the staged host pipeline per ``--pipeline``.  One latency sample per
    full sweep; the per-stage breakdown of the last pipelined sweep rides
    the result."""
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh

    tpu = next((d for d in client.drivers
                if hasattr(d, "lowering_stats")), None)
    corpus = [o for o in data if not reader.is_admission_review(o)]
    r.objects = len(corpus)
    mgr = AuditManager(
        client, lister=lambda: iter(corpus),
        config=AuditConfig(pipeline=pipeline,
                           shard_chunks=shard_chunks),
        evaluator=ShardedEvaluator(tpu, make_mesh(),
                                   flatten_lane=flatten_lane,
                                   collect=collect,
                                   flatten_workers=flatten_workers),
    )
    latencies = []
    violations = 0
    if corpus:
        mgr.audit()  # warmup: vocab + per-bucket jit compile
        t_all0 = time.perf_counter()
        for _ in range(iterations):
            t0 = time.perf_counter()
            run = mgr.audit()
            latencies.append((time.perf_counter() - t0) * 1000)
            violations = sum(run.total_violations.values())
        r.total_eval_s = time.perf_counter() - t_all0
    total_reviews = iterations * len(corpus)
    r.reviews_per_sec = (total_reviews / r.total_eval_s
                         if r.total_eval_s else 0.0)
    _fill_latencies(r, latencies)
    r.violations = violations
    if tpu is not None:
        r.lowering = tpu.lowering_stats()
    stats = dict(mgr.pipe_stats) if mgr.pipe_stats else {}
    stats["schedule"] = ("pipelined" if mgr.perf.get("pipelined")
                        else "serial")
    r.pipeline = stats
    return r


def format_text(results: list) -> str:
    lines = []
    for r in results:
        lines.append(f"engine: {r.engine}")
        lines.append(
            f"  setup: client={r.setup_client_s * 1000:.1f}ms "
            f"templates={r.setup_templates_s * 1000:.1f}ms "
            f"constraints={r.setup_constraints_s * 1000:.1f}ms "
            f"data={r.setup_data_s * 1000:.1f}ms"
        )
        lines.append(
            f"  eval: {r.iterations} iterations x {r.objects} objects in "
            f"{r.total_eval_s:.3f}s -> {r.reviews_per_sec:,.0f} reviews/sec"
        )
        lines.append(
            f"  latency: P50={r.p50_ms:.3f}ms P90={r.p90_ms:.3f}ms "
            f"P99={r.p99_ms:.3f}ms"
        )
        lines.append(f"  violations (last pass): {r.violations}")
        if r.engine == "mutate" and r.lowering is not None:
            lo = r.lowering
            lanes = " ".join(f"{k}={v}" for k, v in
                             sorted(lo.get("lanes", {}).items()))
            lines.append(
                f"  mutate: batched={lo['batched_objs_per_sec']:,.0f} "
                f"obj/s vs host loop={lo['host_objs_per_sec']:,.0f} "
                f"obj/s ({lo['speedup']}x); "
                f"{lo['lowered_mutators']} lowered / "
                f"{lo['host_only_mutators']} host-only mutators; "
                f"lanes: {lanes}")
        elif r.lowering is not None:
            lo = r.lowering
            lines.append(
                f"  lowering: {lo['lowered']}/{lo['templates']} templates "
                f"on the device verdict path "
                f"({lo['fallback_fraction'] * 100:.1f}% interpreter "
                f"fallback)"
            )
            for kind, why in sorted(lo.get("fallback_kinds", {}).items()):
                lines.append(f"    fallback {kind}: {why}")
        if r.pipeline is not None:
            lines.append(f"  pipeline: schedule={r.pipeline.get('schedule')}")
            for name, s in (r.pipeline.get("stages") or {}).items():
                lines.append(
                    f"    stage {name}: busy={s['busy_s']:.3f}s "
                    f"occupancy={s['occupancy'] * 100:.0f}% "
                    f"queue_hw={s['queue_highwater']}"
                )
    return "\n".join(lines)


def run_cli(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator bench")
    p.add_argument("--filename", "-f", action="append", default=[])
    p.add_argument("--engine", default="all",
                   choices=["rego", "cel", "all", "tpu", "sweep",
                            "mutate"],
                   help="'mutate' benchmarks a mutate burst through the "
                        "batched mutlane (vs the host fixed-point loop) "
                        "over the input's mutators + objects; not part "
                        "of 'all' (it needs mutators in the input)")
    p.add_argument("--iterations", "-n", type=int, default=10)
    p.add_argument("--output", "-o", default="", choices=["", "json"])
    p.add_argument("--pipeline", default="auto",
                   choices=["auto", "on", "off", "differential"],
                   help="sweep-engine schedule: staged host pipeline "
                        "(on/auto) vs serial eager-poll (off; auto "
                        "degrades to serial on one-core hosts); "
                        "differential runs both and asserts bit-identical "
                        "output")
    p.add_argument("--flatten-lane", default="auto",
                   choices=["auto", "dict", "raw", "py", "differential"],
                   help="sweep-engine columnizer lane: raw JSON bytes "
                        "through the threaded C columnizer (auto/raw) "
                        "vs the GIL-bound dict walker (dict) vs Python "
                        "(py); differential runs raw THEN dict and "
                        "asserts bit-identical columns")
    p.add_argument("--flatten-workers", type=int, default=0,
                   help="sweep-engine flatten worker processes (see "
                        "the server's --flatten-workers); 0 = "
                        "in-process")
    p.add_argument("--shard-chunks", type=int, default=0,
                   help="sweep-engine chunk packing: K consecutive "
                        "chunks per mesh-wide dispatch; 0/1 = off")
    p.add_argument("--collect", default="reduced",
                   choices=["reduced", "masks", "differential"],
                   help="sweep-engine collect lane: device-side verdict "
                        "reduction (reduced — O(kept) device->host "
                        "bytes) vs the host-fold bit grid (masks); "
                        "differential runs both per chunk and asserts "
                        "totals/kept/occupancy bit-identical")
    p.add_argument("--trace", default="",
                   help="export a Chrome trace-event JSON of the bench "
                        "run's spans to this path (Perfetto-loadable)")
    p.add_argument("--compile-cache", default="",
                   help="on-disk compile cache directory (see python -m "
                        "gatekeeper_tpu --compile-cache): a warm cache "
                        "makes repeat device-engine bench runs skip "
                        "template lowering entirely")
    p.add_argument("--attribution", action="store_true",
                   help="per-template cost attribution table after the "
                        "run: each engine's shared passes apportioned "
                        "across the constraint grid by row occupancy "
                        "(the /debug/cost view, offline)")
    args = p.parse_args(argv)

    try:
        objs = reader.read_sources(args.filename, use_stdin=not args.filename)
    except OSError as e:
        print(f"error: reading: {e}", file=sys.stderr)
        return 1
    if not objs:
        print("no input data identified", file=sys.stderr)
        return 1

    engines = ([args.engine] if args.engine != "all"
               else ["rego", "cel", "all"])
    # span-trace every engine run: an already-active tracer (gator
    # --chaos runs under an outer harness, tests) is reused; otherwise a
    # seeded full-sampling tracer is installed for the bench duration so
    # the per-engine self-time summary below always has data
    from gatekeeper_tpu.observability import (format_span_summary, tracing,
                                              write_chrome_trace)

    tracer = tracing.active_tracer()
    installed = False
    if tracer is None:
        tracer = tracing.Tracer(seed=0)
        tracing.install(tracer)
        installed = True
    from gatekeeper_tpu.observability import costattr as _costattr

    attr = None
    attr_installed = False
    if args.attribution:
        attr = _costattr.active()
        if attr is None:
            attr = _costattr.CostAttribution()
            _costattr.install(attr)
            attr_installed = True
    results = []
    try:
        for engine in engines:
            seen = len(tracer.traces())
            try:
                results.append(run_bench(
                    objs, engine, args.iterations,
                    pipeline=args.pipeline,
                    flatten_lane=args.flatten_lane,
                    collect=args.collect,
                    compile_cache=args.compile_cache,
                    flatten_workers=args.flatten_workers,
                    shard_chunks=args.shard_chunks))
            except Exception as e:
                print(f"error: benchmarking {engine}: {e}", file=sys.stderr)
                return 1
            # one-line top-3-by-self-time span summary per engine run:
            # where the wall actually went, straight from the timeline
            print(f"[{engine}] "
                  + format_span_summary(tracer.traces()[seen:]),
                  file=sys.stderr)
        if args.trace:
            n = write_chrome_trace(args.trace, tracer)
            print(f"trace: {n} events -> {args.trace} (load in "
                  "ui.perfetto.dev or chrome://tracing)", file=sys.stderr)
    finally:
        if installed:
            tracing.uninstall()
        if attr_installed:
            _costattr.uninstall()
    if args.output == "json":
        out = [r.to_dict() for r in results]
        if attr is not None:
            out.append({"attribution": attr.snapshot()})
        print(json.dumps(out, indent=2))
    else:
        print(format_text(results))
        if attr is not None:
            print("cost attribution (per template, all engines):")
            print(attr.table())
    return 0
