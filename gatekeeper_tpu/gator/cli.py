"""gator CLI: offline enforcement points.

Reference: cmd/gator/gator.go (cobra root with subcommands
test / verify / expand / sync / bench / policy).  Usage:

    python -m gatekeeper_tpu.gator test -f <file-or-dir> [...]
"""

from __future__ import annotations

import argparse
import json
import sys

import yaml

from gatekeeper_tpu.gator import reader


def _enforceable_failure(result) -> bool:
    # Reference: cmd/gator/test/test.go:245-255.
    if result.enforcement_action == "deny":
        return True
    return "deny" in (result.scoped_enforcement_actions or [])


def _format_results(results, output: str, stats_entries=None) -> str:
    if output in ("json", "yaml"):
        payload = [
            {
                "target": r.target,
                "msg": r.msg,
                "constraint": r.constraint,
                "metadata": r.metadata,
                "enforcementAction": r.enforcement_action,
                "scopedEnforcementActions": r.scoped_enforcement_actions,
                "violatingObject": r.violating_object,
            }
            for r in results
        ]
        if stats_entries:
            payload = {
                "results": payload,
                "stats": [
                    {
                        "scope": s.scope,
                        "statsFor": s.stats_for,
                        "stats": [
                            {"name": st.name, "value": st.value, "source": st.source}
                            for st in s.stats
                        ],
                    }
                    for s in stats_entries
                ],
            }
        if output == "json":
            return json.dumps(payload, indent=4, default=str)
        return yaml.safe_dump(payload, sort_keys=False)
    # human friendly (reference: cmd/gator/test/test.go:203-230)
    lines = []
    for r in results:
        obj = r.violating_object or {}
        api_version = obj.get("apiVersion", "")
        kind = obj.get("kind", "")
        meta = obj.get("metadata") or {}
        name, ns = meta.get("name", ""), meta.get("namespace", "")
        if ns:
            head = f"{api_version}/{kind} {ns}/{name}"
        else:
            head = f"{api_version}/{kind} {name}"
        cname = (r.constraint.get("metadata") or {}).get("name", "")
        lines.append(f'{head}: ["{cname}"] Message: "{r.msg}"')
    return "\n".join(lines) + ("\n" if lines else "")


def cmd_test(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator test")
    p.add_argument("--filename", "-f", action="append", default=[])
    p.add_argument("--output", "-o", default="")
    p.add_argument("--trace", "-t", action="store_true")
    p.add_argument("--stats", action="store_true")
    p.add_argument("--enable-k8s-native-validation",
                   action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--deny-only", action="store_true")
    args = p.parse_args(argv)

    try:
        objs = reader.read_sources(args.filename, use_stdin=not args.filename)
    except OSError as e:
        print(f"error: reading: {e}", file=sys.stderr)
        return 1
    if not objs:
        print("no input data identified", file=sys.stderr)
        return 1

    from gatekeeper_tpu.gator.test import test as gator_test

    try:
        responses = gator_test(
            objs,
            include_cel=args.enable_k8s_native_validation,
            tracing=args.trace,
            stats=args.stats,
        )
    except Exception as e:  # template/constraint/review errors -> clean exit
        print(f"error: auditing objects: {e}", file=sys.stderr)
        return 1
    results = responses.results()
    if args.deny_only:
        results = [r for r in results if _enforceable_failure(r)]
    out = _format_results(results, args.output,
                          responses.stats_entries if args.stats else None)
    if out:
        print(out, end="" if out.endswith("\n") else "\n")
    return 1 if any(_enforceable_failure(r) for r in results) else 0


def _delegate(module: str):
    def run(argv: list[str]) -> int:
        import importlib

        name = f"gatekeeper_tpu.gator.{module}"
        try:
            mod = importlib.import_module(name)
        except ModuleNotFoundError as e:
            if e.name != name:
                raise  # a real bug inside the module, not a missing command
            print(
                f"error: gator {module} is not available in this build",
                file=sys.stderr,
            )
            return 2
        return mod.run_cli(argv)

    return run


cmd_verify = _delegate("verify")
cmd_expand = _delegate("expand_cmd")
cmd_bench = _delegate("bench")
cmd_sync = _delegate("sync_cmd")
cmd_policy = _delegate("policy_cmd")
cmd_decisions = _delegate("decisions_cmd")
cmd_generate_vap = _delegate("generate_vap_cmd")
cmd_replay = _delegate("replay_cmd")
cmd_triage = _delegate("triage_cmd")


COMMANDS = {
    "test": cmd_test,
    "verify": cmd_verify,
    "expand": cmd_expand,
    "bench": cmd_bench,
    "sync": cmd_sync,
    "policy": cmd_policy,
    "decisions": cmd_decisions,
    "generate-vap": cmd_generate_vap,
    "replay": cmd_replay,
    "triage": cmd_triage,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # JAX_PLATFORMS honored at package import (gatekeeper_tpu/__init__.py)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: gator [--chaos spec.json] "
              "{test|verify|expand|bench|sync|policy|decisions|"
              "generate-vap|replay|triage} [options]")
        return 0
    # global --chaos spec.json: install the deterministic fault-injection
    # plan before any subcommand runs (README 'Failure semantics')
    stripped = []
    chaos = ""
    it = iter(argv)
    for a in it:
        if a == "--chaos":
            chaos = next(it, "")
        elif a.startswith("--chaos="):
            chaos = a.split("=", 1)[1]
        else:
            stripped.append(a)
    argv = stripped
    if chaos:
        from gatekeeper_tpu.resilience import faults

        faults.install(faults.load_chaos_spec(chaos))
        print(f"chaos harness active: {chaos}", file=sys.stderr)
    if not argv:
        print("usage: gator [--chaos spec.json] "
              "{test|verify|expand|bench|sync|policy|decisions|"
              "generate-vap|replay|triage} [options]")
        return 0
    cmd = argv[0]
    fn = COMMANDS.get(cmd)
    if fn is None:
        print(f"unknown command {cmd!r}", file=sys.stderr)
        return 2
    try:
        return fn(argv[1:])
    except BrokenPipeError:
        # downstream pager/head closed the pipe; exit quietly like kubectl
        try:
            sys.stderr.close()
        except Exception:
            pass
        return 1


if __name__ == "__main__":
    sys.exit(main())
