"""``gator triage``: one command, one incident picture.

During a brownout the operator today opens five ``/debug`` endpoints in
five tabs and correlates by hand: which objective is burning, which
degradation maps fired, which template is eating the budget, what the
slowest request actually did, who got shed.  ``triage`` snapshots all
five (``/debug/slo`` + ``/debug/cost`` + ``/debug/overload`` +
``/debug/traces`` + ``/debug/decisions``) and cross-links them into a
single human-readable incident report — breaching objective → active
degradations → top cost templates → slowest exemplar trace → recent
shed decisions — plus a ``--json`` bundle for tooling.

Two modes:

* **live** (``--url http://host:port``): HTTP GET against a running
  webhook's debug endpoints.  ``--cluster`` scopes the SLO view and
  the decision filter to one fleet cluster.
* **offline** (``-f sink.jsonl [--spill DIR]``): the pod is gone; the
  flight-recorder sink (rotated sets read transparently) is the source
  of truth.  Degradations in force are reconstructed from the
  ``overload.degraded`` stamps on recorded decisions; ``--spill``
  inventories per-cluster audit snapshot spills so staleness triage
  has a footing without a live ``/debug/slo``.

    gator triage --url http://localhost:8443
    gator triage -f decisions.jsonl --spill /var/spill -o json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from datetime import datetime, timezone
from typing import Optional

# the five endpoints a triage snapshot covers, keyed by bundle section
ENDPOINTS = {
    "slo": "/debug/slo",
    "cost": "/debug/cost",
    "overload": "/debug/overload",
    "traces": "/debug/traces",
    "decisions": "/debug/decisions",
}


# --- collection -----------------------------------------------------------

def _fetch(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def collect_live(base_url: str, cluster: Optional[str] = None,
                 timeout: float = 5.0, fetch=_fetch) -> dict:
    """Snapshot the five debug endpoints of a live server into one
    bundle.  A failing endpoint becomes ``{"error": ...}`` — an
    incident report with four working sections beats no report (the
    exact failure mode triage runs during)."""
    base = base_url.rstrip("/")
    bundle: dict = {"mode": "live", "url": base}
    for key, path in ENDPOINTS.items():
        url = base + path
        if key == "slo" and cluster:
            url += f"?cluster={cluster}"
        elif key == "decisions" and cluster:
            url += f"?cluster={cluster}"
        try:
            bundle[key] = fetch(url, timeout)
        except (OSError, ValueError, urllib.error.URLError) as e:
            bundle[key] = {"error": f"{path}: {e}"}
    if cluster:
        bundle["cluster"] = cluster
    return bundle


def collect_offline(sink: str, spill: str = "",
                    cluster: Optional[str] = None,
                    limit: int = 500) -> dict:
    """Post-mortem bundle from the flight-recorder sink (+ optional
    snapshot-spill root).  Sections a dead pod can't serve (slo, cost,
    traces) are reconstructed as far as the black box allows: the
    ``overload.degraded`` stamps on decisions say which degradation
    maps were in force, decision ``cost``/``trace_id`` fields give a
    cost/exemplar footing, spill subdirs inventory per-cluster audit
    state."""
    from gatekeeper_tpu.gator.decisions_cmd import read_decisions

    bundle: dict = {"mode": "offline", "sink": sink}
    try:
        bundle["decisions"] = read_decisions(
            sink, cluster=cluster, limit=max(0, limit))
    except OSError as e:
        bundle["decisions"] = {"error": f"{sink}: {e}"}
    if cluster:
        bundle["cluster"] = cluster
    # degradations in force, reconstructed newest-first from the
    # decision stamps ("this allow served a stale namespace")
    seen: dict = {}
    for e in bundle["decisions"].get("decisions", []):
        for name in (e.get("overload") or {}).get("degraded", []):
            if name not in seen:
                seen[name] = e.get("ts", 0.0)
    bundle["overload"] = {
        "reconstructed": True,
        "degraded": [{"action": n, "last_seen_ts": t}
                     for n, t in sorted(seen.items())],
    }
    if spill:
        inv = []
        try:
            for entry in sorted(os.listdir(spill)):
                p = os.path.join(spill, entry)
                if not os.path.isdir(p):
                    continue
                files = sorted(os.listdir(p))
                newest = 0.0
                for f in files:
                    try:
                        newest = max(newest,
                                     os.path.getmtime(os.path.join(p, f)))
                    except OSError:
                        pass
                inv.append({"cluster": entry, "files": len(files),
                            "newest_mtime": newest})
        except OSError as e:
            inv = [{"error": f"{spill}: {e}"}]
        bundle["spill"] = {"root": spill, "clusters": inv}
    return bundle


# --- cross-linking --------------------------------------------------------

def build_report(bundle: dict, top_n: int = 5) -> dict:
    """Cross-link the bundle's sections into the triage chain:
    breaching objective → its active degradations → top cost templates
    → slowest exemplar trace → recent shed decisions.  Pure over the
    bundle dict, so live and offline (and tests) share one path."""
    report: dict = {}

    slo = bundle.get("slo") or {}
    objectives = slo.get("objectives") or []
    breaching = [ev for ev in objectives if ev.get("breach")]
    non_compliant = [ev for ev in objectives
                     if not ev.get("compliant", True)
                     and not ev.get("breach")]
    report["objectives_total"] = len(objectives)
    report["breaching"] = breaching
    report["non_compliant"] = non_compliant

    # active degradations: prefer the authoritative overload view,
    # fall back to the per-objective SLO view / offline reconstruction
    ovl = bundle.get("overload") or {}
    degraded = ovl.get("degraded") or []
    if not degraded:
        degraded = [
            {"action": a, "objectives": [ev.get("name", "")]}
            for ev in objectives
            for a in ev.get("degradation_active", [])]
    report["degraded"] = degraded

    cost = bundle.get("cost") or {}
    report["top_templates"] = (cost.get("top") or [])[:top_n]
    report["top_tenants"] = (cost.get("tenants") or [])[:top_n]

    traces = (bundle.get("traces") or {}).get("traces") or []
    slowest = sorted(traces, key=lambda t: -t.get("duration_s", 0.0))
    report["slowest_traces"] = slowest[:top_n]

    decisions = (bundle.get("decisions") or {}).get("decisions") or []
    sheds = [e for e in decisions if e.get("decision") == "shed"]
    report["recent_sheds"] = sheds[:top_n]
    report["decision_counts"] = _count_by(decisions, "decision")

    # exemplar linkage: the slowest kept trace back to its decision(s)
    by_trace = {}
    for e in decisions:
        tid = e.get("trace_id", "")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    report["exemplar"] = None
    for t in slowest:
        linked = by_trace.get(t.get("trace_id", ""))
        if linked:
            report["exemplar"] = {"trace": t, "decisions": linked}
            break
    if report["exemplar"] is None and slowest:
        report["exemplar"] = {"trace": slowest[0], "decisions": []}

    # the chain, one entry per breaching objective
    chains = []
    for ev in breaching:
        chains.append({
            "objective": ev.get("name", ""),
            "cluster": ev.get("cluster", ""),
            "tier": ev.get("breach_tier", ""),
            "burn": ev.get("burn"),
            "sli": ev.get("sli"),
            "target": ev.get("target"),
            "degradations": list(ev.get("degradation_active") or []),
            "next_degradation": _next_action(ev),
            "top_template": (report["top_templates"][0]["template"]
                             if report["top_templates"] else ""),
            "slowest_trace": (report["slowest_traces"][0]["trace_id"]
                              if report["slowest_traces"] else ""),
            "recent_sheds": len(sheds),
        })
    report["chains"] = chains
    return report


def _next_action(ev: dict) -> str:
    deg = ev.get("degradation") or []
    active = ev.get("degradation_active") or []
    return deg[len(active)] if len(active) < len(deg) else ""


def _count_by(entries: list, key: str) -> dict:
    out: dict = {}
    for e in entries:
        out[e.get(key, "")] = out.get(e.get(key, ""), 0) + 1
    return out


# --- rendering ------------------------------------------------------------

def _ts(t: float) -> str:
    try:
        return datetime.fromtimestamp(
            t, tz=timezone.utc).strftime("%H:%M:%S")
    except (OverflowError, OSError, ValueError):
        return str(t)


def render(bundle: dict, report: dict) -> str:
    """The human incident report."""
    lines = []
    src = bundle.get("url") or bundle.get("sink") or ""
    head = f"gatekeeper triage — {bundle.get('mode', '?')} {src}"
    if bundle.get("cluster"):
        head += f" [cluster {bundle['cluster']}]"
    lines += [head, "=" * len(head), ""]

    for key in ("slo", "cost", "overload", "traces", "decisions"):
        err = (bundle.get(key) or {}).get("error")
        if err:
            lines.append(f"!! {key}: unavailable ({err})")
    if any((bundle.get(k) or {}).get("error")
           for k in ("slo", "cost", "overload", "traces", "decisions")):
        lines.append("")

    n_breach = len(report["breaching"])
    if bundle.get("slo") is not None and "error" not in \
            (bundle.get("slo") or {}):
        lines.append(f"SLO: {n_breach}/{report['objectives_total']} "
                     f"objectives breaching"
                     + (f", {len(report['non_compliant'])} non-compliant"
                        if report["non_compliant"] else ""))
        for ev in report["breaching"]:
            lines.append(
                f"  ! {ev.get('name', '?')}  sli={ev.get('sli')}  "
                f"target={ev.get('target')}  burn={ev.get('burn')}"
                f"  tier={ev.get('breach_tier', '')}")
            active = ev.get("degradation_active") or []
            if active:
                lines.append("      degradations active: "
                             + " -> ".join(active))
            nxt = _next_action(ev)
            if nxt:
                lines.append(f"      next if sustained: {nxt}")
        lines.append("")

    if report["degraded"]:
        lines.append("Degradations in force:")
        for d in report["degraded"]:
            tag = d.get("action", "?")
            if d.get("cluster"):
                tag += f"@{d['cluster']}"
            holders = d.get("objectives") or []
            extra = f"  held by: {', '.join(holders)}" if holders else ""
            if d.get("last_seen_ts"):
                extra += f"  last seen {_ts(d['last_seen_ts'])}"
            lines.append(f"  {tag}{extra}")
        lines.append("")

    if report["top_templates"]:
        lines.append("Top cost templates:")
        for i, t in enumerate(report["top_templates"], 1):
            lines.append(f"  {i}. {t.get('template', '?')}  "
                         f"{t.get('seconds', 0.0)}s over "
                         f"{t.get('passes', 0)} passes")
        lines.append("")

    ex = report["exemplar"]
    if ex is not None:
        t = ex["trace"]
        lines.append(f"Slowest exemplar trace: {t.get('trace_id', '?')}  "
                     f"root={t.get('root', '?')}  "
                     f"{t.get('duration_s', 0.0):.3f}s  "
                     f"{t.get('n_spans', 0)} spans")
        for e in ex["decisions"][:3]:
            lines.append(f"  -> decision: {e.get('decision', '?')} "
                         f"uid={e.get('uid', '')} "
                         f"cost={e.get('cost', 0.0)}")
        lines.append("")

    decs = bundle.get("decisions") or {}
    if "error" not in decs:
        counts = report["decision_counts"]
        if counts:
            lines.append("Decisions ("
                         + ", ".join(f"{k}={v}" for k, v
                                     in sorted(counts.items())) + "):")
        if report["recent_sheds"]:
            for e in report["recent_sheds"]:
                tag = f"  {_ts(e.get('ts', 0.0))}  shed  " \
                      f"uid={e.get('uid', '')}"
                if e.get("tenant"):
                    tag += f"  tenant={e['tenant']}"
                if e.get("reason"):
                    tag += f"  reason={e['reason']}"
                deg = (e.get("overload") or {}).get("degraded")
                if deg:
                    tag += f"  [degraded: {', '.join(deg)}]"
                lines.append(tag)
        elif counts:
            lines.append("  (no recent sheds)")
        lines.append("")

    spill = bundle.get("spill")
    if spill:
        lines.append(f"Audit snapshot spills under {spill['root']}:")
        for c in spill["clusters"]:
            if "error" in c:
                lines.append(f"  !! {c['error']}")
            else:
                lines.append(f"  {c['cluster']}: {c['files']} files, "
                             f"newest {_ts(c['newest_mtime'])}")
        lines.append("")

    if report["chains"]:
        lines.append("Chain:")
        for c in report["chains"]:
            seg = [f"{c['objective']} breaching "
                   f"(burn {c['burn']}, {c['tier'] or 'n/a'})"]
            if c["degradations"]:
                seg.append("activated " + ", ".join(c["degradations"]))
            if c["top_template"]:
                seg.append(f"top template {c['top_template']}")
            if c["slowest_trace"]:
                seg.append(f"slowest trace {c['slowest_trace'][:16]}")
            seg.append(f"{c['recent_sheds']} recent sheds")
            lines.append("  " + " -> ".join(seg))
    elif bundle.get("slo") is not None and n_breach == 0 and \
            "error" not in (bundle.get("slo") or {}):
        lines.append("Chain: all objectives compliant — nothing to "
                     "triage.")
    elif bundle.get("slo") is None:
        lines.append("Chain: no SLO view in this bundle (offline "
                     "sink only) — see degradation stamps above.")
    return "\n".join(lines).rstrip() + "\n"


# --- CLI ------------------------------------------------------------------

def run_cli(argv: list) -> int:
    p = argparse.ArgumentParser(
        prog="gator triage",
        description="one-shot incident snapshot: /debug/slo + cost + "
                    "overload + traces + decisions, cross-linked into "
                    "a triage chain (live --url or offline -f sink)")
    p.add_argument("--url", default="",
                   help="live mode: base URL of a running webhook "
                        "(e.g. http://localhost:8443)")
    p.add_argument("--filename", "-f", default="",
                   help="offline mode: flight-recorder JSONL sink "
                        "(rotated sets read transparently)")
    p.add_argument("--spill", default="",
                   help="offline mode: snapshot-spill root to "
                        "inventory per-cluster audit state")
    p.add_argument("--cluster", default=None,
                   help="scope the SLO view + decision filter to one "
                        "fleet cluster")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="live mode: per-endpoint HTTP timeout seconds")
    p.add_argument("--top", type=int, default=5,
                   help="entries per report section")
    p.add_argument("--output", "-o", default="",
                   choices=["", "json"],
                   help="json: the raw bundle + report (default: "
                        "human incident report)")
    args = p.parse_args(argv)
    if bool(args.url) == bool(args.filename):
        print("error: exactly one of --url (live) or -f (offline) "
              "is required", file=sys.stderr)
        return 2
    if args.url:
        bundle = collect_live(args.url, cluster=args.cluster,
                              timeout=args.timeout)
    else:
        bundle = collect_offline(args.filename, spill=args.spill,
                                 cluster=args.cluster)
    bundle["collected_at"] = time.time()
    report = build_report(bundle, top_n=max(1, args.top))
    if args.output == "json":
        print(json.dumps({"bundle": bundle, "report": report},
                         indent=2, default=str))
    else:
        print(render(bundle, report), end="")
    # exit 1 when something is breaching: triage in a script gates on it
    return 1 if report["chains"] else 0
