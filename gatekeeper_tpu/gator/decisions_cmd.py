"""``gator decisions``: offline reader over the flight-recorder JSONL
sink (the operator's black box).

``/debug/decisions`` answers "why was THIS request shed at 14:02" while
the process is alive; this command answers it from the ``--flight-
recorder-sink`` file after the pod is gone — same filter semantics
(uid, half-open ``[--since, --until)`` time range, decision kinds,
tenant, fleet cluster), most-recent-first, bounded by ``--limit``.

    gator decisions -f decisions.jsonl --decision shed --tenant team-a \
        --since 1700000000 --until 1700000060 -o json

Timestamps accept unix seconds or ISO-8601 (``2026-08-04T14:02:00``,
interpreted as UTC when no offset is given — sink ``ts`` fields are
``time.time()`` epochs)."""

from __future__ import annotations

import argparse
import json
import sys
from datetime import datetime, timezone
from typing import Optional


def _parse_ts(v: Optional[str]) -> Optional[float]:
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        pass
    try:
        dt = datetime.fromisoformat(v)
    except ValueError:
        raise ValueError(f"bad timestamp {v!r} (unix seconds or ISO-8601)")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def read_decisions(path: str, uid: str = "",
                   since: Optional[float] = None,
                   until: Optional[float] = None,
                   kinds: Optional[set] = None,
                   tenant: Optional[str] = None,
                   cluster: Optional[str] = None,
                   limit: int = 100) -> dict:
    """Load + filter a flight-recorder JSONL sink.  Returns the same
    payload shape as ``FlightRecorder.snapshot`` (``decisions`` most
    recent first, ``matched`` when any filter applied) so tooling built
    against ``/debug/decisions`` reads both.  A size-rotated sink set
    (``path.N`` … ``path.1`` + ``path``, see ``--flight-recorder-sink-
    max-mb``) reads transparently oldest-first as one stream.
    Malformed lines are counted, never fatal — a black box that
    crashes its reader is no black box."""
    from gatekeeper_tpu.observability.flightrec import rotated_paths

    decisions: list = []
    malformed = 0
    truncated = 0
    total = 0
    paths = rotated_paths(path) or [path]
    for part in paths:
        with open(part) as f:
            for raw in f:
                ends_nl = raw.endswith("\n")
                line = raw.strip()
                if not line:
                    continue
                total += 1
                try:
                    e = json.loads(line)
                except ValueError:
                    # a final line with no newline is a crashed
                    # recorder's torn tail, not sink corruption —
                    # count it apart
                    if ends_nl:
                        malformed += 1
                    else:
                        truncated += 1
                    continue
                if not isinstance(e, dict):
                    # valid JSON but not a record (e.g. a bare number
                    # from a corrupted merge) — same skip-and-count
                    # contract
                    malformed += 1
                    continue
                if uid and e.get("uid") != uid:
                    continue
                ts = float(e.get("ts", 0.0) or 0.0)
                if since is not None and ts < since:
                    continue
                if until is not None and ts >= until:
                    continue
                if kinds and e.get("decision") not in kinds:
                    continue
                if tenant is not None and e.get("tenant", "") != tenant:
                    continue
                if cluster is not None and \
                        e.get("cluster", "") != cluster:
                    continue
                decisions.append(e)
    filtered = bool(uid or since is not None or until is not None
                    or kinds or tenant is not None
                    or cluster is not None)
    decisions.reverse()  # most recent first, like /debug/decisions
    out = {"recorded": total, "sink": path,
           "decisions": decisions[: max(0, limit)]}
    if len(paths) > 1:
        out["rotated_files"] = len(paths)
    if filtered:
        out["matched"] = len(decisions)
    if malformed:
        out["malformed"] = malformed
    if truncated:
        out["truncated"] = truncated
    return out


def _table(doc: dict) -> str:
    rows = doc["decisions"]
    if not rows:
        return "(no matching decisions)"
    cols = ("ts", "endpoint", "decision", "uid", "kind", "namespace",
            "tenant", "cluster", "priority", "reason", "cost")
    rendered = [[("%.3f" % e["ts"]) if c == "ts" and "ts" in e
                 else str(e.get(c, "")) for c in cols] for e in rows]
    widths = [max(len(c), *(len(r[i]) for r in rendered))
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(widths[i]) for i, c in enumerate(cols))]
    for r in rendered:
        lines.append("  ".join(v.ljust(widths[i])
                               for i, v in enumerate(r)))
    return "\n".join(lines)


def run_cli(argv: list) -> int:
    p = argparse.ArgumentParser(
        prog="gator decisions",
        description="offline reader over a flight-recorder JSONL sink "
                    "(--flight-recorder-sink); same filter semantics as "
                    "/debug/decisions")
    p.add_argument("--filename", "-f", required=True,
                   help="flight-recorder JSONL sink file")
    p.add_argument("--uid", default="", help="one request uid's history")
    p.add_argument("--since", default="",
                   help="keep decisions at/after this time (unix seconds "
                        "or ISO-8601; half-open [since, until))")
    p.add_argument("--until", default="",
                   help="keep decisions before this time")
    p.add_argument("--decision", action="append", default=[],
                   help="decision kind filter (repeatable or comma list: "
                        "allow|deny|shed|error|deadline)")
    p.add_argument("--tenant", default=None,
                   help="one tenant's decisions (the QoS/attribution "
                        "tenant key: namespace or serviceaccount)")
    p.add_argument("--cluster", default=None,
                   help="one cluster's decisions (the fleet axis: the "
                        "serving cluster id recorded per decision)")
    p.add_argument("--limit", type=int, default=100,
                   help="max decisions printed (most recent first)")
    p.add_argument("--output", "-o", default="",
                   choices=["", "json", "table"],
                   help="output format (default: human table)")
    args = p.parse_args(argv)
    try:
        since = _parse_ts(args.since)
        until = _parse_ts(args.until)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    kinds = {k for v in args.decision for k in v.split(",") if k}
    try:
        doc = read_decisions(args.filename, uid=args.uid, since=since,
                             until=until, kinds=kinds or None,
                             tenant=args.tenant, cluster=args.cluster,
                             limit=args.limit)
    except OSError as e:
        print(f"error: reading sink: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(doc, indent=2, default=str))
    else:
        print(_table(doc))
        extra = f"{len(doc['decisions'])} shown"
        if "matched" in doc:
            extra += f" of {doc['matched']} matched"
        extra += f" ({doc['recorded']} lines in sink"
        if doc.get("malformed"):
            extra += f", {doc['malformed']} malformed"
        if doc.get("truncated"):
            extra += f", {doc['truncated']} truncated"
        print(f"-- {extra})")
    return 0
