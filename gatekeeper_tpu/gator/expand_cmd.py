"""gator expand: offline expansion preview (reference: cmd/gator/expand).

Reads resources + ExpansionTemplates + mutators, prints the resultant
resources as YAML documents (sorted keys, --- separated), or writes them to
--outputs.
"""

from __future__ import annotations

import argparse
import sys

import yaml

from gatekeeper_tpu.expansion.expander import Expander
from gatekeeper_tpu.gator import reader


def run_cli(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator expand")
    p.add_argument("--filename", "-f", action="append", default=[])
    p.add_argument("--output", "-o", default="",
                   help="write to file instead of stdout")
    p.add_argument("--format", default="yaml", choices=["yaml", "json"])
    p.add_argument("--lane", default="host",
                   choices=["host", "batched", "differential"],
                   help="'host' walks the recursive per-object reference "
                        "path; 'batched' expands level-synchronously "
                        "through the mutlane expansion stage (resultants "
                        "batch-mutate in one columnar pass per level); "
                        "'differential' runs BOTH and asserts identical "
                        "resultants")
    args = p.parse_args(argv)

    try:
        objs = reader.read_sources(args.filename, use_stdin=not args.filename)
    except OSError as e:
        print(f"error: reading: {e}", file=sys.stderr)
        return 1
    if not objs:
        print("no input data identified", file=sys.stderr)
        return 1

    try:
        resultants = _expand(objs, args.lane)
    except Exception as e:
        print(f"error: expanding resources: {e}", file=sys.stderr)
        return 1

    docs = [r.obj for r in resultants]
    if args.lane == "differential":
        print(f"differential: batched lane identical to the host walk "
              f"({len(docs)} resultants)", file=sys.stderr)
    if args.format == "json":
        import json

        out = json.dumps(docs, indent=4)
    else:
        out = "---\n".join(
            yaml.safe_dump(d, sort_keys=True, default_flow_style=False)
            for d in docs
        )
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    else:
        sys.stdout.write(out)
    return 0


def _expand(objs, lane: str) -> list:
    """Resultants of every base under the chosen lane (the CLI's
    sequential per-object order)."""
    import copy

    def host(objects):
        expander = Expander(objects)
        out = []
        for obj in objects:
            out.extend(expander.expand(obj))
        return out

    if lane == "host":
        return host(objs)
    from gatekeeper_tpu.mutlane import BatchedExpander

    # the host walk mutates bases in place; isolate each lane's input so
    # a differential run compares two independent expansions
    batched_objs = copy.deepcopy(objs) if lane == "differential" else objs
    batched = BatchedExpander(
        batched_objs, differential=lane == "differential")
    resultants = batched.expand_all(batched_objs)
    if lane == "differential":
        want = host(objs)
        got_docs = [r.obj for r in resultants]
        want_docs = [r.obj for r in want]
        if got_docs != want_docs:
            raise AssertionError(
                "expansion differential mismatch: batched lane diverged "
                f"from the host walk ({len(got_docs)} vs "
                f"{len(want_docs)} resultants)")
        for g, w in zip(resultants, want):
            if (g.template_name, g.enforcement_action) != \
                    (w.template_name, w.enforcement_action):
                raise AssertionError(
                    "expansion differential mismatch: template/"
                    "enforcement metadata diverged")
    return resultants
