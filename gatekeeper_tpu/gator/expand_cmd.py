"""gator expand: offline expansion preview (reference: cmd/gator/expand).

Reads resources + ExpansionTemplates + mutators, prints the resultant
resources as YAML documents (sorted keys, --- separated), or writes them to
--outputs.
"""

from __future__ import annotations

import argparse
import sys

import yaml

from gatekeeper_tpu.expansion.expander import Expander
from gatekeeper_tpu.gator import reader


def run_cli(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator expand")
    p.add_argument("--filename", "-f", action="append", default=[])
    p.add_argument("--output", "-o", default="",
                   help="write to file instead of stdout")
    p.add_argument("--format", default="yaml", choices=["yaml", "json"])
    args = p.parse_args(argv)

    try:
        objs = reader.read_sources(args.filename, use_stdin=not args.filename)
    except OSError as e:
        print(f"error: reading: {e}", file=sys.stderr)
        return 1
    if not objs:
        print("no input data identified", file=sys.stderr)
        return 1

    try:
        expander = Expander(objs)
        resultants = []
        for obj in objs:
            resultants.extend(expander.expand(obj))
    except Exception as e:
        print(f"error: expanding resources: {e}", file=sys.stderr)
        return 1

    docs = [r.obj for r in resultants]
    if args.format == "json":
        import json

        out = json.dumps(docs, indent=4)
    else:
        out = "---\n".join(
            yaml.safe_dump(d, sort_keys=True, default_flow_style=False)
            for d in docs
        )
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    else:
        sys.stdout.write(out)
    return 0
