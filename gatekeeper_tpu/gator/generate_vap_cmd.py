"""gator generate-vap: emit ValidatingAdmissionPolicy manifests.

Reference: the VAP codegen path (k8scel/transform/make_vap_objects.go +
manageVAP at constrainttemplate_controller.go:503) — the fourth
enforcement point: policies shift INTO the apiserver.  The CEL driver and
``template_to_vap``/``constraint_to_vap_binding`` landed with the seed;
this is the offline CLI surface over them.

Reads ConstraintTemplates (K8sNativeValidation source) and their
constraints from ``-f`` files/dirs, prints one VAP per CEL template and
one VAPB per constraint as YAML documents (or ``--format json``).
Templates without a CEL source are skipped with a note (Rego-only
templates have no in-apiserver form); ``--require-generate-vap``
restricts emission to templates whose source opts in via
``generateVAP: true`` (the controller's gating).
"""

from __future__ import annotations

import argparse
import sys

import yaml

from gatekeeper_tpu.gator import reader


def run_cli(argv: list[str]) -> int:
    p = argparse.ArgumentParser(prog="gator generate-vap")
    p.add_argument("--filename", "-f", action="append", default=[])
    p.add_argument("--output", "-o", default="",
                   help="write to file instead of stdout")
    p.add_argument("--format", default="yaml", choices=["yaml", "json"])
    p.add_argument("--require-generate-vap", action="store_true",
                   help="emit only templates whose CEL source sets "
                        "generateVAP: true (the in-cluster controller's "
                        "gating); default emits every CEL template")
    args = p.parse_args(argv)

    try:
        objs = reader.read_sources(args.filename, use_stdin=not args.filename)
    except OSError as e:
        print(f"error: reading: {e}", file=sys.stderr)
        return 1
    if not objs:
        print("no input data identified", file=sys.stderr)
        return 1

    try:
        docs, skipped = generate(objs, args.require_generate_vap)
    except Exception as e:
        print(f"error: generating VAP manifests: {e}", file=sys.stderr)
        return 1
    for kind, why in skipped:
        print(f"skipped {kind}: {why}", file=sys.stderr)
    if not docs:
        print("no CEL templates to generate from", file=sys.stderr)
        return 1
    if args.format == "json":
        import json

        out = json.dumps(docs, indent=4)
    else:
        out = "---\n".join(
            yaml.safe_dump(d, sort_keys=True, default_flow_style=False)
            for d in docs
        )
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    else:
        sys.stdout.write(out)
    return 0


def generate(objs, require_generate_vap: bool = False) -> tuple:
    """(manifests, skipped): VAPs for CEL templates + VAPBs for their
    constraints, in input order.  ``skipped`` lists (template kind,
    reason) for non-CEL or opted-out templates."""
    from gatekeeper_tpu.apis.constraints import (CONSTRAINTS_GROUP,
                                                 Constraint)
    from gatekeeper_tpu.apis.templates import ConstraintTemplate
    from gatekeeper_tpu.drivers.cel_driver import CELDriver

    driver = CELDriver()
    templates: dict = {}  # kind -> ConstraintTemplate
    constraints: list = []
    for obj in objs:
        kind = obj.get("kind", "")
        group = (obj.get("apiVersion", "") or "").split("/")[0]
        if kind == "ConstraintTemplate":
            t = ConstraintTemplate.from_unstructured(obj)
            templates[t.kind] = t
        elif group == CONSTRAINTS_GROUP:
            constraints.append(Constraint.from_unstructured(obj))
    docs: list = []
    skipped: list = []
    emitted: set = set()
    for kind, t in templates.items():
        if not driver.has_source_for(t):
            skipped.append((kind, "no K8sNativeValidation (CEL) source"))
            continue
        driver.add_template(t)
        compiled = driver._templates.get(kind)
        if require_generate_vap and not getattr(compiled, "generate_vap",
                                                False):
            skipped.append((kind, "generateVAP not set"))
            continue
        docs.append(driver.template_to_vap(t))
        emitted.add(kind)
    for con in constraints:
        if con.kind in emitted:
            docs.append(driver.constraint_to_vap_binding(
                con, templates[con.kind]))
    return docs, skipped
