"""gator policy: policy-library package manager.

Reference: pkg/gator/policy/ (search/install/upgrade against a catalog,
artifacts fetched via ORAS OCI pull, pkg/oci/oci.go:27).  Here the catalog
is a YAML index and artifact refs resolve to:

- a bundle directory (template.yaml + samples/ + suite.yaml),
- a .tar / .tar.gz bundle, or
- an OCI image-layout directory (oci-layout + index.json + blobs/...,
  the on-disk format ORAS produces) whose layers are tar(.gz) bundles.

Network refs (http/https/oci://) are recognized but refused: this build
runs without egress; mirror the artifact locally and point the catalog at
the mirror.

Catalog format:

    policies:
      - name: requiredlabels
        description: Requires resources to contain specified labels.
        versions:
          - version: 1.1.2
            ref: bundles/requiredlabels-1.1.2.tar.gz

Installed state is tracked in <target>/.gator-policies.yaml.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import tarfile

import yaml

STATE_FILE = ".gator-policies.yaml"


class PolicyError(Exception):
    pass


# --- catalog ---------------------------------------------------------------


def load_catalog(path: str) -> list:
    if path.startswith(("http://", "https://", "oci://")):
        raise PolicyError(
            f"remote catalog {path!r} not supported in this build (no "
            "network egress); mirror it locally and pass the file path"
        )
    if os.path.isdir(path):
        path = os.path.join(path, "catalog.yaml")
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    entries = doc.get("policies") or []
    for e in entries:
        if not e.get("name"):
            raise PolicyError("catalog entry without a name")
        e.setdefault("versions", [])
    return entries


def _resolve(entries: list, name: str, version: str = ""):
    for e in entries:
        if e["name"] != name:
            continue
        versions = e["versions"]
        if not versions:
            raise PolicyError(f"catalog entry {name!r} has no versions")
        if not version:
            return e, versions[-1]  # catalog order: last = latest
        for v in versions:
            if str(v.get("version")) == version:
                return e, v
        raise PolicyError(f"{name!r} has no version {version!r}")
    raise PolicyError(f"policy {name!r} not found in catalog")


# --- artifact unpack -------------------------------------------------------


def _extract_tar(fileobj, dest: str) -> None:
    with tarfile.open(fileobj=fileobj, mode="r:*") as tf:
        for member in tf.getmembers():
            # refuse path traversal
            target = os.path.normpath(os.path.join(dest, member.name))
            if not target.startswith(os.path.abspath(dest)):
                raise PolicyError(f"unsafe path in bundle: {member.name}")
        try:
            tf.extractall(dest, filter="data")
        except TypeError:  # Python < 3.12: no filter argument
            tf.extractall(dest)


def _unpack_oci_layout(layout_dir: str, dest: str) -> None:
    """Minimal OCI image-layout reader: index.json -> manifest -> layers
    (each a tar or tar.gz bundle)."""
    with open(os.path.join(layout_dir, "index.json")) as f:
        index = json.load(f)
    manifests = index.get("manifests") or []
    if not manifests:
        raise PolicyError("OCI layout with no manifests")

    def blob(digest: str) -> str:
        algo, hexd = digest.split(":", 1)
        return os.path.join(layout_dir, "blobs", algo, hexd)

    with open(blob(manifests[0]["digest"])) as f:
        manifest = json.load(f)
    layers = manifest.get("layers") or []
    if not layers:
        raise PolicyError("OCI manifest with no layers")
    for layer in layers:
        with open(blob(layer["digest"]), "rb") as f:
            _extract_tar(io.BytesIO(f.read()), dest)


# Transport plug (reference: the ORAS client behind pkg/oci/oci.go:27).
# A deployment with egress registers real fetchers here — e.g.
#   REMOTE_TRANSPORTS["oci://"] = my_oras_pull  # (ref, dest) -> None
# and fetch_bundle routes through them; this build ships only the
# refusing stubs because the environment has no network egress.
REMOTE_TRANSPORTS: dict = {}


def _refuse_remote(ref: str, dest: str) -> None:
    raise PolicyError(
        f"remote artifact {ref!r} not supported in this build (no "
        "network egress); mirror it locally"
    )


for _scheme in ("http://", "https://", "oci://"):
    REMOTE_TRANSPORTS.setdefault(_scheme, _refuse_remote)


def fetch_bundle(ref: str, catalog_dir: str, dest: str) -> None:
    """Materialize the bundle at ``ref`` (relative to the catalog) into
    ``dest`` so that dest/template.yaml exists."""
    for scheme, fetch in REMOTE_TRANSPORTS.items():
        if ref.startswith(scheme):
            fetch(ref, dest)
            return
    src = ref if os.path.isabs(ref) else os.path.join(catalog_dir, ref)
    if not os.path.exists(src):
        raise PolicyError(f"artifact {src!r} does not exist")
    os.makedirs(dest, exist_ok=True)
    if os.path.isdir(src):
        if os.path.exists(os.path.join(src, "index.json")):
            _unpack_oci_layout(src, dest)
        else:
            shutil.copytree(src, dest, dirs_exist_ok=True)
    else:
        with open(src, "rb") as f:
            _extract_tar(f, dest)
    # bundles may nest a single top-level dir; flatten it
    if not os.path.exists(os.path.join(dest, "template.yaml")):
        subdirs = [d for d in os.listdir(dest)
                   if os.path.isdir(os.path.join(dest, d))]
        if len(subdirs) == 1 and os.path.exists(
                os.path.join(dest, subdirs[0], "template.yaml")):
            inner = os.path.join(dest, subdirs[0])
            for item in os.listdir(inner):
                shutil.move(os.path.join(inner, item),
                            os.path.join(dest, item))
            os.rmdir(inner)
    if not os.path.exists(os.path.join(dest, "template.yaml")):
        raise PolicyError("bundle does not contain template.yaml")


# --- installed-state tracking ---------------------------------------------


def _state_path(target: str) -> str:
    return os.path.join(target, STATE_FILE)


def load_state(target: str) -> dict:
    try:
        with open(_state_path(target)) as f:
            return yaml.safe_load(f) or {}
    except FileNotFoundError:
        return {}


def save_state(target: str, state: dict) -> None:
    os.makedirs(target, exist_ok=True)
    with open(_state_path(target), "w") as f:
        yaml.safe_dump(state, f, sort_keys=True)


# --- operations ------------------------------------------------------------


def search(catalog: str, term: str = "") -> list:
    entries = load_catalog(catalog)
    term = term.lower()
    out = []
    for e in entries:
        hay = f"{e['name']} {e.get('description', '')}".lower()
        if term and term not in hay:
            continue
        latest = (e["versions"][-1].get("version", "?")
                  if e["versions"] else "?")
        out.append((e["name"], str(latest), e.get("description", "")))
    return out


def install(catalog: str, name: str, target: str, version: str = "",
            upgrade: bool = False) -> str:
    entries = load_catalog(catalog)
    entry, ver = _resolve(entries, name, version)
    vstr = str(ver.get("version", "?"))
    state = load_state(target)
    cur = state.get(name, {}).get("version")
    if cur is not None and not upgrade:
        raise PolicyError(
            f"{name!r} {cur} already installed (use upgrade)")
    if cur == vstr and upgrade:
        return f"{name} {vstr} already up to date"
    catalog_dir = os.path.dirname(os.path.abspath(
        catalog if not os.path.isdir(catalog)
        else os.path.join(catalog, "catalog.yaml")))
    dest = os.path.join(target, name)
    if os.path.exists(dest):
        shutil.rmtree(dest)
    fetch_bundle(ver.get("ref", ""), catalog_dir, dest)
    state[name] = {"version": vstr, "ref": ver.get("ref", "")}
    save_state(target, state)
    verb = "upgraded to" if cur else "installed"
    return f"{name} {verb} {vstr}"


def remove(target: str, name: str) -> str:
    state = load_state(target)
    if name not in state:
        raise PolicyError(f"{name!r} is not installed")
    dest = os.path.join(target, name)
    if os.path.exists(dest):
        shutil.rmtree(dest)
    del state[name]
    save_state(target, state)
    return f"{name} removed"


def list_installed(target: str) -> list:
    state = load_state(target)
    return sorted((n, str(v.get("version", "?")))
                  for n, v in state.items())


# --- CLI -------------------------------------------------------------------


def run_cli(argv) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="gator policy",
        description="policy-library package manager (local catalogs + "
                    "OCI image layouts; remote refs refused: no egress)")
    psub = p.add_subparsers(dest="policy_cmd", required=True)

    sp = psub.add_parser("search", help="search the catalog")
    sp.add_argument("term", nargs="?", default="")
    sp.add_argument("--catalog", required=True)

    ip = psub.add_parser("install", help="install a policy bundle")
    ip.add_argument("name")
    ip.add_argument("--catalog", required=True)
    ip.add_argument("--target", default="library")
    ip.add_argument("--version", default="")

    up = psub.add_parser("upgrade", help="upgrade an installed policy")
    up.add_argument("name")
    up.add_argument("--catalog", required=True)
    up.add_argument("--target", default="library")
    up.add_argument("--version", default="")

    rp = psub.add_parser("remove", help="remove an installed policy")
    rp.add_argument("name")
    rp.add_argument("--target", default="library")

    lp = psub.add_parser("list", help="list installed policies")
    lp.add_argument("--target", default="library")

    args = p.parse_args(argv)
    try:
        if args.policy_cmd == "search":
            rows = search(args.catalog, args.term)
            for name, ver, desc in rows:
                print(f"{name}\t{ver}\t{desc}")
            if not rows:
                print("no policies matched", file=sys.stderr)
        elif args.policy_cmd == "install":
            print(install(args.catalog, args.name, args.target,
                          args.version))
        elif args.policy_cmd == "upgrade":
            print(install(args.catalog, args.name, args.target,
                          args.version, upgrade=True))
        elif args.policy_cmd == "remove":
            print(remove(args.target, args.name))
        elif args.policy_cmd == "list":
            for name, ver in list_installed(args.target):
                print(f"{name}\t{ver}")
    except PolicyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0
