"""RawJSON: a lazy dict proxy over raw JSON bytes.

The audit sweep's host bottleneck is JSON-dict materialization + dict
walking (~15µs/object on one core, ROADMAP.md "Performance levers").  The
threaded native flattener (native/flattenjsonmod.c) columnizes raw bytes
directly with the GIL released — but the surrounding planes (match slow
paths, message rendering for hits, expansion) still expect dict objects.

``RawJSON`` bridges the two: it subclasses ``dict`` (so every
``isinstance(o, dict)`` check in the target/match/mutation planes holds)
but stays *empty* until first access, at which point it parses ``raw``
once and self-populates.  The flatten fast path recognizes the class and
reads ``.raw`` without ever triggering the parse; only slow-path matchers
and violation rendering — a tiny fraction of a sweep — pay for
materialization.
"""

from __future__ import annotations

import json


class RawJSON(dict):
    """Lazy dict view of one JSON document (bytes)."""

    __slots__ = ("raw", "_loaded")

    def __init__(self, raw: bytes):
        super().__init__()
        self.raw = raw
        self._loaded = False

    def _load(self):
        if not self._loaded:
            self._loaded = True
            obj = json.loads(self.raw)
            if isinstance(obj, dict):
                dict.update(self, obj)

    # -- read AND write accessors trigger the parse -----------------------
    # (a write before the parse would otherwise be silently overwritten
    # when a later read triggers _load's dict.update; and the mutation
    # plane's clear()/update() restore pattern must see loaded state)
    def __getitem__(self, k):
        self._load()
        return dict.__getitem__(self, k)

    def __setitem__(self, k, v):
        self._load()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._load()
        dict.__delitem__(self, k)

    def update(self, *args, **kwargs):
        self._load()
        dict.update(self, *args, **kwargs)

    def setdefault(self, k, default=None):
        self._load()
        return dict.setdefault(self, k, default)

    def pop(self, *args):
        self._load()
        return dict.pop(self, *args)

    def popitem(self):
        self._load()
        return dict.popitem(self)

    def clear(self):
        self._load()  # mark loaded so raw can't resurrect cleared keys
        dict.clear(self)

    def get(self, k, default=None):
        self._load()
        return dict.get(self, k, default)

    def __contains__(self, k):
        self._load()
        return dict.__contains__(self, k)

    def __iter__(self):
        self._load()
        return dict.__iter__(self)

    def __len__(self):
        self._load()
        return dict.__len__(self)

    def __bool__(self):
        self._load()
        return dict.__len__(self) > 0

    def keys(self):
        self._load()
        return dict.keys(self)

    def values(self):
        self._load()
        return dict.values(self)

    def items(self):
        self._load()
        return dict.items(self)

    def __eq__(self, other):
        self._load()
        if isinstance(other, RawJSON):
            other._load()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):  # dicts are unhashable; keep that behavior
        raise TypeError("unhashable type: 'RawJSON'")

    def copy(self):
        self._load()
        return dict(self)

    def __reduce__(self):
        # a materialized (possibly mutated) instance must round-trip its
        # CURRENT dict state — reconstructing from .raw would silently
        # revert mutations under copy/deepcopy/pickle
        if not self._loaded:
            return (RawJSON, (self.raw,))
        return (_restore_loaded, (self.raw, dict(self)))

    def __repr__(self):
        if not self._loaded:
            return f"RawJSON(<{len(self.raw)} bytes, unparsed>)"
        return f"RawJSON({dict.__repr__(self)})"


def _restore_loaded(raw: bytes, state: dict) -> "RawJSON":
    r = RawJSON(raw)
    r._loaded = True
    dict.update(r, state)
    return r


def as_raw(obj) -> "RawJSON":
    """Wrap a dict (serializing once) or bytes into a RawJSON."""
    if isinstance(obj, RawJSON):
        return obj
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return RawJSON(bytes(obj))
    return RawJSON(json.dumps(obj, separators=(",", ":")).encode())
