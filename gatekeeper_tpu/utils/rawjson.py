"""RawJSON: a lazy dict proxy over raw JSON bytes.

The audit sweep's host bottleneck is JSON-dict materialization + dict
walking (~15µs/object on one core, ROADMAP.md "Performance levers").  The
threaded native flattener (native/flattenjsonmod.c) columnizes raw bytes
directly with the GIL released — but the surrounding planes (match slow
paths, message rendering for hits, expansion) still expect dict objects.

``RawJSON`` bridges the two: it subclasses ``dict`` (so every
``isinstance(o, dict)`` check in the target/match/mutation planes holds)
but stays *empty* until first access, at which point it parses ``raw``
once and self-populates.  The flatten fast path recognizes the class and
reads ``.raw`` without ever triggering the parse; only slow-path matchers
and violation rendering — a tiny fraction of a sweep — pay for
materialization.
"""

from __future__ import annotations

import json


class RawJSON(dict):
    """Lazy dict view of one JSON document (bytes)."""

    __slots__ = ("raw", "_loaded")

    def __init__(self, raw: bytes):
        super().__init__()
        self.raw = raw
        self._loaded = False

    def _load(self):
        if not self._loaded:
            self._loaded = True
            obj = json.loads(self.raw)
            if isinstance(obj, dict):
                dict.update(self, obj)

    # -- read AND write accessors trigger the parse -----------------------
    # (a write before the parse would otherwise be silently overwritten
    # when a later read triggers _load's dict.update; and the mutation
    # plane's clear()/update() restore pattern must see loaded state)
    def __getitem__(self, k):
        self._load()
        return dict.__getitem__(self, k)

    def __setitem__(self, k, v):
        self._load()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._load()
        dict.__delitem__(self, k)

    def update(self, *args, **kwargs):
        self._load()
        dict.update(self, *args, **kwargs)

    def setdefault(self, k, default=None):
        self._load()
        return dict.setdefault(self, k, default)

    def pop(self, *args):
        self._load()
        return dict.pop(self, *args)

    def popitem(self):
        self._load()
        return dict.popitem(self)

    def clear(self):
        self._load()  # mark loaded so raw can't resurrect cleared keys
        dict.clear(self)

    def get(self, k, default=None):
        self._load()
        return dict.get(self, k, default)

    def __contains__(self, k):
        self._load()
        return dict.__contains__(self, k)

    def __iter__(self):
        self._load()
        return dict.__iter__(self)

    def __len__(self):
        self._load()
        return dict.__len__(self)

    def __bool__(self):
        self._load()
        return dict.__len__(self) > 0

    def keys(self):
        self._load()
        return dict.keys(self)

    def values(self):
        self._load()
        return dict.values(self)

    def items(self):
        self._load()
        return dict.items(self)

    def __eq__(self, other):
        self._load()
        if isinstance(other, RawJSON):
            other._load()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):  # dicts are unhashable; keep that behavior
        raise TypeError("unhashable type: 'RawJSON'")

    def copy(self):
        self._load()
        return dict(self)

    def __reduce__(self):
        # a materialized (possibly mutated) instance must round-trip its
        # CURRENT dict state — reconstructing from .raw would silently
        # revert mutations under copy/deepcopy/pickle
        if not self._loaded:
            return (RawJSON, (self.raw,))
        return (_restore_loaded, (self.raw, dict(self)))

    def __repr__(self):
        if not self._loaded:
            return f"RawJSON(<{len(self.raw)} bytes, unparsed>)"
        return f"RawJSON({dict.__repr__(self)})"


def _restore_loaded(raw: bytes, state: dict) -> "RawJSON":
    r = RawJSON(raw)
    r._loaded = True
    dict.update(r, state)
    return r


import re as _re

# head fast path: K8s serializations open with apiVersion/kind (in either
# order) — one anchored match on the first bytes resolves the top-level
# kind with no depth scan at all
_HEAD_KIND = _re.compile(
    rb'^\{"(?:apiVersion":"[^"\\]*",")?kind":"([^"\\]*)"')
_KIND_VAL = _re.compile(rb'\s*:\s*"([^"\\]*)"')


def peek_kind(obj) -> str:
    """Top-level ``kind`` of a K8s object WITHOUT materializing a RawJSON.

    The audit kind router classifies every listed object; going through
    ``obj.get("kind")`` would parse all N objects and push every chunk of
    the sweep onto the re-serialization path (a full json.dumps per
    object per chunk).  For an unloaded RawJSON this scans the raw bytes:
    find a ``"kind"`` key occurrence, verify by prefix scan that it sits
    at object depth 1 outside any string, then read its string value —
    K8s serializations carry kind in the first bytes, so the verify scan
    is ~a dozen bytes.  Falls back to the parse when the scan is
    inconclusive (escaped value, non-string kind)."""
    if not isinstance(obj, RawJSON) or obj._loaded:
        v = obj.get("kind")
        return v if isinstance(v, str) else ""
    raw = obj.raw
    m = _HEAD_KIND.match(raw)
    if m:
        try:
            return m.group(1).decode("utf-8")
        except UnicodeDecodeError:
            pass
    pos = 0
    # depth/in-string/escape state carried incrementally across candidate
    # positions: each '"kind"' occurrence only scans the bytes since the
    # previous one (a prefix rescan from 0 per candidate is O(occurrences
    # x object_size) on objects whose top-level kind serializes after
    # nested kind keys — ownerReferences, roleRef)
    depth = 0
    instr = False
    esc = False
    scanned = 0
    mv = memoryview(raw)
    while True:
        pos = raw.find(b'"kind"', pos)
        if pos < 0:
            return ""  # no "kind" bytes at all: the key cannot exist
        for b in mv[scanned:pos]:
            if esc:
                esc = False
            elif b == 0x5C:  # backslash
                esc = True
            elif b == 0x22:  # quote
                instr = not instr
            elif not instr:
                if b == 0x7B or b == 0x5B:  # { [
                    depth += 1
                elif b == 0x7D or b == 0x5D:  # } ]
                    depth -= 1
        scanned = pos
        if depth == 1 and not instr:
            m = _KIND_VAL.match(raw, pos + 6)
            if m:
                try:
                    return m.group(1).decode("utf-8")
                except UnicodeDecodeError:
                    break  # fall through to the exact parse
            break  # escaped or non-string value: exact parse
        pos += 6
    v = obj.get("kind")  # exact fallback (materializes this one object)
    return v if isinstance(v, str) else ""


def as_raw(obj) -> "RawJSON":
    """Wrap a dict (serializing once) or bytes into a RawJSON."""
    if isinstance(obj, RawJSON):
        return obj
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return RawJSON(bytes(obj))
    return RawJSON(json.dumps(obj, separators=(",", ":")).encode())


# one regex pass yields only strings and structural brackets; strings
# are consumed wholesale so brackets inside them never count
_STRUCT_TOKEN = _re.compile(rb'"(?:[^"\\]|\\.)*"|[{}\[\]]')


def split_list_items(page: bytes) -> tuple:
    """Split a K8s ``*List`` response into per-item raw byte spans.

    Returns ``(item_spans, envelope)``: the raw bytes of each element of
    the top-level ``items`` array, plus the envelope dict (the page with
    ``items`` replaced by ``[]`` — apiVersion/kind/metadata.continue
    parse from a few hundred bytes instead of the whole page).  This is
    the zero-copy half of the raw-bytes flatten path: list pages never
    materialize their items as Python dicts.

    Raises ``ValueError`` when the page has no top-level ``items`` array
    or carries non-object elements — callers fall back to the parsed
    page.
    """
    items: list = []
    depth = 0
    in_items = False
    pend_key = None  # (token bytes, token end) of the last depth-1 string
    items_lb = items_rb = -1
    elem_start = -1
    for m in _STRUCT_TOKEN.finditer(page):
        t = page[m.start()]
        if t == 0x22:  # string
            if depth == 1 and not in_items:
                pend_key = (m.group(), m.end())
            elif in_items and depth == 2:
                raise ValueError("non-object element in items")
            continue
        if t == 0x7B:  # {
            if in_items and depth == 2:
                elem_start = m.start()
            depth += 1
        elif t == 0x5B:  # [
            # an '[' at depth 1 in valid JSON can only be a key's value:
            # it opens the items array iff that key is "items"
            if (depth == 1 and not in_items and pend_key is not None
                    and pend_key[0] == b'"items"'
                    and page[pend_key[1]:m.start()].strip() == b":"):
                in_items = True
                items_lb = m.start()
            depth += 1
        elif t == 0x7D:  # }
            depth -= 1
            if in_items and depth == 2 and elem_start >= 0:
                items.append(page[elem_start:m.end()])
                elem_start = -1
        else:  # ]
            depth -= 1
            if in_items and depth == 1:
                in_items = False
                items_rb = m.end()
    if items_lb < 0 or items_rb < 0 or depth != 0:
        raise ValueError("no top-level items array")
    envelope = json.loads(page[:items_lb] + b"[]" + page[items_rb:])
    return items, envelope


def backfill_gvk(raw: bytes, api_version: str, kind: str) -> bytes:
    """Prepend apiVersion/kind defaults to one split List item (List
    responses omit them on elements).  JSON duplicate keys are last-wins
    (both ``json.loads`` and the native parser), so an item carrying
    either key keeps its own value — the byte-splice equivalent of
    ``dict.setdefault``, and it lands the keys where ``peek_kind``'s
    head fast path reads them."""
    if not raw.startswith(b"{"):
        return raw
    head = b'{"apiVersion":%s,"kind":%s' % (
        json.dumps(api_version).encode(), json.dumps(kind).encode())
    rest = raw[1:]
    if rest.lstrip().startswith(b"}"):
        return head + rest
    return head + b"," + rest
