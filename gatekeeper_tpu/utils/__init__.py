from gatekeeper_tpu.utils.unstructured import (  # noqa: F401
    deep_get,
    deep_set,
    deep_copy,
    load_yaml_objects,
)
