"""Synthetic cluster workload shaped for the shipped policy library.

The bench workload of BASELINE config #2: a realistic mix of Kubernetes
objects whose fields exercise every template in ``library/general`` —
Pods (images, resources, probes, securityContext, host namespaces,
sysctls, hostPath volumes), Services (NodePort, externalIPs,
annotations), Ingresses (duplicate hosts for the referential
uniqueingresshost join, wildcard hosts, missing TLS), Deployments
(replica counts), Namespaces (labels) and RBAC bindings
(system:anonymous subjects).

Field distributions are tuned against the library's own
``samples/constraint.yaml`` parameters so each constraint sees a ~1-5%
violation rate — the mostly-compliant regime a production audit sweep
runs in (reference apparatus: pkg/gator/bench/bench.go:44, webhook bench
fixtures pkg/webhook/policy_benchmark_test.go:251).
"""

from __future__ import annotations

import glob
import os
import random
from typing import Optional

# allowedrepos sample allows the 'openpolicyagent/' repo prefix
_REPOS_OK = ["openpolicyagent/"]
_REPOS_BAD = ["docker.io/rando/", "quay.io/other/"]
_HOSTS = [f"svc-{i}.example.com" for i in range(40)]
_BAD_CAPS = ["NET_ADMIN", "SYS_TIME", "CHOWN", "KILL", "AUDIT_WRITE"]
# forbiddensysctls sample forbids kernel.* and net.core.somaxconn
_SYSCTLS_OK = ["net.ipv4.tcp_syncookies", "net.ipv4.ip_local_port_range"]
_SYSCTLS_BAD = ["kernel.shm_rmid_forced", "net.core.somaxconn"]


def _digest(rng: random.Random) -> str:
    return "".join(rng.choice("0123456789abcdef") for _ in range(64))


# a realistic cluster runs a bounded set of distinct images (pods share
# them), not one unique digest per pod — the pool also bounds vocab growth
_IMAGE_POOL: list = []


def _image(rng: random.Random) -> str:
    # imagedigests requires @sha256 digests; allowedrepos requires the
    # openpolicyagent/ prefix; disallowedtags forbids :latest
    if not _IMAGE_POOL:
        prng = random.Random(12345)
        for i in range(480):
            repo = (prng.choice(_REPOS_BAD) if prng.random() < 0.02
                    else prng.choice(_REPOS_OK))
            name = f"app{i % 60}"
            r = prng.random()
            if r < 0.006:
                _IMAGE_POOL.append(f"{repo}{name}:latest")
            elif r < 0.012:
                _IMAGE_POOL.append(f"{repo}{name}:v{prng.randrange(1, 9)}")
            elif r < 0.016:
                _IMAGE_POOL.append(f"{repo}{name}")  # untagged, no digest
            else:
                _IMAGE_POOL.append(
                    f"{repo}{name}@sha256:{_digest(prng)}")
    return rng.choice(_IMAGE_POOL)


def _container(rng: random.Random, j: int) -> dict:
    # per-container rates are ~1/3 of the per-pod target: multi-container
    # pods compound per-container misses into per-pod violation rates
    c: dict = {"name": f"c{j}", "image": _image(rng)}
    # containerlimits sample caps: cpu 200m, memory 1Gi
    if rng.random() < 0.99:
        limits = {
            "memory": rng.choice(["128Mi", "256Mi", "512Mi", "1Gi"])
            if rng.random() < 0.995 else "4Gi",
            "cpu": rng.choice(["50m", "100m", "200m"])
            if rng.random() < 0.995 else "2",
        }
        c["resources"] = {"limits": limits}
    sc: dict = {}
    if rng.random() < 0.015:
        sc["privileged"] = True
    if rng.random() < 0.985:
        sc["readOnlyRootFilesystem"] = True
    elif rng.random() < 0.3:
        sc["readOnlyRootFilesystem"] = False
    # capabilities sample: must drop NET_RAW; may add only NET_BIND_SERVICE
    caps: dict = {}
    if rng.random() < 0.99:
        caps["drop"] = ["NET_RAW"]
    if rng.random() < 0.03:
        caps["add"] = (["NET_BIND_SERVICE"] if rng.random() < 0.7
                       else [rng.choice(_BAD_CAPS)])
    if caps:
        sc["capabilities"] = caps
    if sc:
        c["securityContext"] = sc
    if rng.random() < 0.99:
        c["livenessProbe"] = {"tcpSocket": {"port": 8080}}
    if rng.random() < 0.99:
        c["readinessProbe"] = {"httpGet": {"path": "/", "port": 8080}}
    if rng.random() < 0.3:
        ports = [{"containerPort": 8080}]
        if rng.random() < 0.03:
            # hostnetworkingports sample allows hostPorts in [80, 9000]
            ports[0]["hostPort"] = (rng.randrange(80, 9000)
                                    if rng.random() < 0.6
                                    else rng.randrange(9001, 65535))
        c["ports"] = ports
    return c


def _pod(rng: random.Random, i: int, ns: str) -> dict:
    spec: dict = {
        "containers": [
            _container(rng, j) for j in range(rng.randrange(1, 4))
        ],
    }
    if rng.random() < 0.02:
        spec["hostNetwork"] = True
    if rng.random() < 0.015:
        spec["hostPID"] = True
    if rng.random() < 0.015:
        spec["hostIPC"] = True
    # automounttoken requires automountServiceAccountToken == false
    if rng.random() < 0.96:
        spec["automountServiceAccountToken"] = False
    if rng.random() < 0.03:
        name = (rng.choice(_SYSCTLS_OK) if rng.random() < 0.5
                else rng.choice(_SYSCTLS_BAD))
        spec["securityContext"] = {
            "sysctls": [{"name": name, "value": "1"}]
        }
    if rng.random() < 0.12:
        vols = [{"name": "data", "emptyDir": {}}]
        if rng.random() < 0.25:
            # hostfilesystem sample allows the /var/log prefix only
            vols.append({"name": "host",
                         "hostPath": {"path": "/var/log/app"
                                      if rng.random() < 0.8
                                      else rng.choice(["/etc", "/dev"])}})
        spec["volumes"] = vols
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": f"pod-{i}", "namespace": ns,
            "labels": {"app": f"app{rng.randrange(50)}"},
        },
        "spec": spec,
    }


def _service(rng: random.Random, i: int, ns: str) -> dict:
    spec: dict = {"ports": [{"port": 80}],
                  "type": "NodePort" if rng.random() < 0.02 else "ClusterIP"}
    if rng.random() < 0.03:
        # externalip sample allows 203.0.113.0 only
        spec["externalIPs"] = ["203.0.113.0" if rng.random() < 0.6
                               else f"203.0.113.{rng.randrange(1, 255)}"]
    meta: dict = {"name": f"svc-{i}", "namespace": ns}
    # requiredannotations sample requires a8r.io/owner matching .+
    if rng.random() < 0.97:
        meta["annotations"] = {"a8r.io/owner": f"team-{rng.randrange(8)}"}
    return {"apiVersion": "v1", "kind": "Service", "metadata": meta,
            "spec": spec}


def _ingress(rng: random.Random, i: int, ns: str) -> dict:
    # ~4% draw from a shared host pool (duplicates violate the referential
    # uniqueingresshost policy); the rest are unique
    host = rng.choice(_HOSTS) if rng.random() < 0.04 \
        else f"ing-{i}.example.com"
    if rng.random() < 0.02:
        host = "*.example.com"
    spec: dict = {"rules": [{"host": host}]}
    meta: dict = {"name": f"ing-{i}", "namespace": ns}
    # httpsonly requires spec.tls AND the allow-http=false annotation
    if rng.random() < 0.97:
        spec["tls"] = [{"hosts": [host]}]
        meta["annotations"] = {"kubernetes.io/ingress.allow-http": "false"}
    return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
            "metadata": meta, "spec": spec}


def _deployment(rng: random.Random, i: int, ns: str) -> dict:
    # replicalimits sample range: 3..50
    replicas = (rng.choice([3, 3, 5, 8, 12, 20])
                if rng.random() < 0.96 else rng.choice([1, 60]))
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": f"dep-{i}", "namespace": ns},
            "spec": {"replicas": replicas,
                     "template": {"spec": {"containers": [
                         _container(rng, 0)]}}}}


def _namespace(rng: random.Random, i: int) -> dict:
    labels = {}
    if rng.random() < 0.96:
        # requiredlabels sample: owner must match ^[a-zA-Z]+.agilebank.demo$
        labels["owner"] = f"user{chr(97 + rng.randrange(26))}.agilebank.demo"
    if rng.random() < 0.8:
        labels["gatekeeper"] = "true"
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": f"ns-{i}", "labels": labels}}


def _binding(rng: random.Random, i: int, ns: str) -> dict:
    cluster = rng.random() < 0.4
    subject = {"kind": "User", "apiGroup": "rbac.authorization.k8s.io",
               "name": "system:anonymous" if rng.random() < 0.03
               else f"user-{rng.randrange(30)}"}
    obj = {"apiVersion": "rbac.authorization.k8s.io/v1",
           "kind": "ClusterRoleBinding" if cluster else "RoleBinding",
           "metadata": {"name": f"rb-{i}"},
           "subjects": [subject],
           "roleRef": {"kind": "ClusterRole", "name": "view",
                       "apiGroup": "rbac.authorization.k8s.io"}}
    if not cluster:
        obj["metadata"]["namespace"] = ns
    return obj


def iter_cluster_objects(n: int, seed: int = 0):
    """Streaming generator behind :func:`make_cluster_objects` — the
    O(chunk)-memory audit path consumes objects one at a time instead of
    materializing a 1M-object list (reference analog: paged List +
    disk spill, pkg/audit/manager.go:502-561)."""
    rng = random.Random(seed)
    for i in range(n):
        ns = f"ns-{rng.randrange(40)}"
        r = rng.random()
        if r < 0.70:
            yield _pod(rng, i, ns)
        elif r < 0.78:
            yield _service(rng, i, ns)
        elif r < 0.86:
            yield _ingress(rng, i, ns)
        elif r < 0.91:
            yield _deployment(rng, i, ns)
        elif r < 0.96:
            yield _namespace(rng, i)
        else:
            yield _binding(rng, i, ns)


def make_cluster_objects(n: int, seed: int = 0) -> list[dict]:
    """``n`` objects: ~70% Pods, 8% Services, 8% Ingresses, 5%
    Deployments, 5% Namespaces, 4% RBAC bindings."""
    return list(iter_cluster_objects(n, seed))


def library_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "..", "..", "library")


def load_library(client, library: Optional[str] = None,
                 skip_kinds: tuple = ()) -> tuple[int, int]:
    """Add every shipped library template + its sample constraint to
    ``client``.  Returns (n_templates, n_constraints)."""
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    library = library or library_dir()
    nt = nc = 0
    for tpath in sorted(
            glob.glob(os.path.join(library, "general", "*",
                                   "template.yaml")) +
            glob.glob(os.path.join(library, "pod-security-policy", "*",
                                   "template.yaml"))):
        doc = load_yaml_file(tpath)[0]
        kind = (doc.get("spec", {}).get("crd", {}).get("spec", {})
                .get("names", {}).get("kind", ""))
        if kind in skip_kinds:
            continue
        client.add_template(doc)
        nt += 1
        cpath = os.path.join(os.path.dirname(tpath), "samples",
                             "constraint.yaml")
        if os.path.exists(cpath):
            for cdoc in load_yaml_file(cpath):
                client.add_constraint(cdoc)
                nc += 1
    return nt, nc
