"""Helpers over "unstructured" Kubernetes objects (plain dict/list/scalar trees).

The reference manipulates ``unstructured.Unstructured`` everywhere; our analog is
the raw JSON tree.  These helpers are the host-side utilities shared by the
target handler, mutation system and flattener.
"""

from __future__ import annotations

import copy
from typing import Any, Iterator, Sequence

import yaml


def deep_get(obj: Any, path: Sequence[str], default: Any = None) -> Any:
    """Walk ``path`` through nested dicts; returns ``default`` on any miss."""
    cur = obj
    for p in path:
        if not isinstance(cur, dict) or p not in cur:
            return default
        cur = cur[p]
    return cur


def deep_set(obj: dict, path: Sequence[str], value: Any) -> None:
    """Set ``value`` at ``path``, creating intermediate dicts."""
    cur = obj
    for p in path[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[path[-1]] = value


def deep_copy(obj: Any) -> Any:
    return copy.deepcopy(obj)


def load_yaml_objects(text: str) -> list[dict]:
    """Parse a (possibly multi-document) YAML string into object dicts."""
    return [doc for doc in yaml.safe_load_all(text) if doc]


def load_yaml_file(path: str) -> list[dict]:
    with open(path) as f:
        return load_yaml_objects(f.read())


def gvk_of(obj: dict) -> tuple[str, str, str]:
    """(group, version, kind) of an unstructured object.

    ``apiVersion`` is ``group/version`` or bare ``version`` for the core group
    (reference: apimachinery GroupVersionKind semantics).
    """
    api_version = obj.get("apiVersion", "") or ""
    kind = obj.get("kind", "") or ""
    if not isinstance(api_version, str):  # tolerate malformed docs
        api_version = ""
    if not isinstance(kind, str):
        kind = ""
    if "/" in api_version:
        group, version = api_version.split("/", 1)
    else:
        group, version = "", api_version
    return group, version, kind


def api_version_of(group: str, version: str) -> str:
    return f"{group}/{version}" if group else version


def name_of(obj: dict) -> str:
    return deep_get(obj, ("metadata", "name"), "") or ""


def namespace_of(obj: dict) -> str:
    return deep_get(obj, ("metadata", "namespace"), "") or ""


def labels_of(obj: dict) -> dict:
    return deep_get(obj, ("metadata", "labels"), {}) or {}


def iter_leaves(obj: Any, prefix: tuple = ()) -> Iterator[tuple[tuple, Any]]:
    """Yield (path-tuple, scalar) pairs over the whole tree.

    List indices appear as ints in the path.  Used by the flattener and by
    differential tests.
    """
    if isinstance(obj, dict):
        if not obj:
            yield prefix, obj
        for k, v in obj.items():
            yield from iter_leaves(v, prefix + (k,))
    elif isinstance(obj, list):
        if not obj:
            yield prefix, obj
        for i, v in enumerate(obj):
            yield from iter_leaves(v, prefix + (i,))
    else:
        yield prefix, obj
