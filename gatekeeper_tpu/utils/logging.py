"""Canonical structured logging.

Reference: pkg/logging/logging.go — fixed semantic keys shared by deny logs
(policy.go:276-296), audit violation logs (manager.go:1218-1245) and template
lifecycle logs, so log pipelines can rely on stable field names.
"""

from __future__ import annotations

import json
import logging
import sys
import time

# canonical keys (logging.go:52)
PROCESS = "process"
DETAILS = "details"
EVENT_TYPE = "event_type"
TEMPLATE_NAME = "template_name"
CONSTRAINT_GROUP = "constraint_group"
CONSTRAINT_API_VERSION = "constraint_api_version"
CONSTRAINT_KIND = "constraint_kind"
CONSTRAINT_NAME = "constraint_name"
CONSTRAINT_NAMESPACE = "constraint_namespace"
CONSTRAINT_ACTION = "constraint_action"
CONSTRAINT_ANNOTATIONS = "constraint_annotations"
CONSTRAINT_STATUS = "constraint_status"
AUDIT_ID = "audit_id"
RESOURCE_GROUP = "resource_group"
RESOURCE_KIND = "resource_kind"
RESOURCE_API_VERSION = "resource_api_version"
RESOURCE_NAMESPACE = "resource_namespace"
RESOURCE_NAME = "resource_name"
RESOURCE_LABELS = "resource_labels"
REQUEST_USERNAME = "request_username"

_logger = logging.getLogger("gatekeeper_tpu")
if not _logger.handlers:
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter("%(message)s"))
    _logger.addHandler(handler)
    _logger.setLevel(logging.INFO)


class _WarnSampler:
    """WARN+ rate limit: at most ``rate`` warning/error lines per second
    (reference: main.go zap sampling, WARN+ sampled 100/s); dropped-line
    counts surface on the next emitted record."""

    def __init__(self, rate: int = 100):
        self.rate = rate
        self._window = 0.0
        self._count = 0
        self._dropped = 0

    def admit(self) -> tuple:
        """(emit: bool, dropped_since_last_emit: int)"""
        now = time.monotonic()
        if now - self._window >= 1.0:
            self._window = now
            self._count = 0
        if self._count >= self.rate:
            self._dropped += 1
            return False, 0
        self._count += 1
        dropped, self._dropped = self._dropped, 0
        return True, dropped


_warn_sampler = _WarnSampler()


def log_event(level: str, msg: str, **fields) -> None:
    """zapr-style JSON line with canonical keys; WARN+ is sampled."""
    record = {"level": level, "ts": time.time(), "msg": msg}
    record.update({k: v for k, v in fields.items() if v is not None})
    if level in ("warning", "error"):
        emit, dropped = _warn_sampler.admit()
        if not emit:
            return
        if dropped:
            record["sampled_dropped"] = dropped
    line = json.dumps(record, default=str)
    if level == "error":
        _logger.error(line)
    elif level == "warning":
        _logger.warning(line)
    else:
        _logger.info(line)


def log_deny(result, req, process: str = "admission") -> None:
    """Structured deny log (reference: policy.go:276-296 with
    --log-denies)."""
    constraint = result.constraint or {}
    meta = constraint.get("metadata") or {}
    kind = (req.kind or {}) if req is not None else {}
    log_event(
        "info",
        "denied admission: " + result.msg,
        **{
            PROCESS: process,
            EVENT_TYPE: "violation",
            CONSTRAINT_GROUP: "constraints.gatekeeper.sh",
            CONSTRAINT_KIND: constraint.get("kind", ""),
            CONSTRAINT_NAME: meta.get("name", ""),
            CONSTRAINT_ACTION: result.enforcement_action,
            RESOURCE_GROUP: kind.get("group", ""),
            RESOURCE_KIND: kind.get("kind", ""),
            RESOURCE_NAMESPACE: req.namespace if req else "",
            RESOURCE_NAME: req.name if req else "",
            REQUEST_USERNAME: (req.user_info or {}).get("username", "")
            if req else "",
        },
    )


def log_audit_violation(violation, audit_id: str) -> None:
    """Reference: manager.go:1218-1245."""
    constraint = violation.constraint
    log_event(
        "info",
        violation.message,
        **{
            PROCESS: "audit",
            EVENT_TYPE: "violation_audited",
            AUDIT_ID: audit_id,
            CONSTRAINT_KIND: constraint.kind,
            CONSTRAINT_NAME: constraint.name,
            CONSTRAINT_ACTION: violation.enforcement_action,
            RESOURCE_GROUP: violation.group,
            RESOURCE_API_VERSION: violation.version,
            RESOURCE_KIND: violation.kind,
            RESOURCE_NAMESPACE: violation.namespace,
            RESOURCE_NAME: violation.name,
        },
    )
