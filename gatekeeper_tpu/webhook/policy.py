"""Validating admission handler (reference: pkg/webhook/policy.go).

Flow (§3.1 of SURVEY.md):
- self-management bypass for the gatekeeper service account (policy.go:142)
- gatekeeper-resource meta-validation fast path (templates/constraints/
  expansion templates/mutators validated structurally, policy.go:359-401)
- namespace exclusion via the process excluder (policy.go:170)
- review of the request (+ expansion resultants, policy.go:602-646)
- deny/warn partition by enforcement action incl. scoped (policy.go:256-353)

TPU twist: instead of the reference's goroutine-per-request capped by a
semaphore (policy.go:116-120), requests funnel into a **microbatching lane**
(Batcher) that coalesces concurrent admissions into one ``review_batch``
call on the device; latency is bounded by the batch window.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.apis.constraints import (
    CONSTRAINTS_GROUP,
    Constraint,
    ConstraintError,
    WEBHOOK_EP,
)
from gatekeeper_tpu.apis.templates import ConstraintTemplate, TemplateError
from gatekeeper_tpu.expansion.system import EXPANSION_GROUP, ExpansionTemplate
from gatekeeper_tpu.match.match import SOURCE_GENERATED, SOURCE_ORIGINAL
from gatekeeper_tpu.mutation.mutators import (
    MUTATIONS_GROUP,
    MUTATOR_KINDS,
    MutatorError,
    from_unstructured as mutator_from_unstructured,
)
from gatekeeper_tpu.expansion.system import ExpansionError
from gatekeeper_tpu.target.review import AdmissionRequest, AugmentedReview
from gatekeeper_tpu.utils.unstructured import gvk_of

GATEKEEPER_SA_PREFIX = "system:serviceaccount:gatekeeper-system:"
TEMPLATES_GROUP = "templates.gatekeeper.sh"


@dataclass
class ValidationResponse:
    allowed: bool
    message: str = ""
    code: int = 200
    warnings: list = field(default_factory=list)
    uid: str = ""
    # shed under failurePolicy=Fail: the server emits an HTTP Retry-After
    # header with this hint (0 = no header)
    retry_after_s: float = 0.0


def parse_admission_review(body: dict) -> AdmissionRequest:
    req = body.get("request") or {}
    return AdmissionRequest(
        uid=req.get("uid", "") or "",
        kind=req.get("kind") or {},
        resource=req.get("resource") or {},
        sub_resource=req.get("subResource", "") or "",
        name=req.get("name", "") or "",
        namespace=req.get("namespace", "") or "",
        operation=req.get("operation", "") or "",
        user_info=req.get("userInfo") or {},
        object=req.get("object"),
        old_object=req.get("oldObject"),
        dry_run=bool(req.get("dryRun", False)),
        options=req.get("options"),
    )


class ValidationHandler:
    def __init__(
        self,
        client,
        expansion_system=None,
        process_excluder=None,
        namespace_lookup=None,  # name -> Namespace object
        batcher: Optional["Batcher"] = None,
        log_denies: bool = False,
        event_sink=None,
        metrics=None,
        fail_open: bool = False,
        trace_config=None,  # callable -> list of Config trace entries
        log_stats: bool = False,  # --log-stats-admission
        deadline_budget_s: float = 0.0,  # hard per-request wall budget
        failure_policy: Optional[str] = None,  # "ignore" | "fail"
        overload=None,  # resilience.overload.OverloadController
        snapshot=None,  # snapshot.ClusterSnapshot (warm lookup cache)
        cluster: str = "",  # fleet serving scope (labels SLIs/decisions)
    ):
        self.client = client
        self.expansion_system = expansion_system
        self.process_excluder = process_excluder
        self.namespace_lookup = namespace_lookup or (lambda name: None)
        # warm referential cache: with the resident cluster snapshot
        # active, namespace lookups serve from its watch-synced rows —
        # no per-request apiserver GET on the admission hot path (the
        # reference's cached client with API-reader fallback,
        # policy.go:694-702, minus the fallback GET for cache hits)
        self.snapshot = snapshot
        self.batcher = batcher
        self.log_denies = log_denies
        self.event_sink = event_sink
        self.metrics = metrics
        # failurePolicy (reference ValidatingWebhookConfiguration
        # failurePolicy: Ignore fails open / Fail fails closed); the
        # legacy fail_open flag maps onto it
        if failure_policy is None:
            failure_policy = "ignore" if fail_open else "fail"
        if failure_policy not in ("ignore", "fail"):
            raise ValueError(f"failure_policy must be ignore|fail, "
                             f"got {failure_policy!r}")
        self.failure_policy = failure_policy
        self.fail_open = failure_policy == "ignore"
        # deadline budget: 0 disables the guard (review runs inline on
        # the server's handler thread, exactly the pre-resilience path)
        self.deadline_budget_s = float(deadline_budget_s or 0.0)
        self.trace_config = trace_config
        self.log_stats = log_stats
        # overload protection (resilience/overload.py): the admission
        # gate in front of the review, plus the caches its brownout
        # ladder degrades onto — a bounded stale namespace-lookup cache
        # and a per-kind matched-constraint estimate for the cost model
        self.overload = overload
        # fleet mode: a non-empty cluster id labels this handler's
        # latency histogram / status counters / decisions with
        # {cluster}, feeding the per-cluster SLO objectives; "" keeps
        # the single-cluster series unlabeled (bit-identical)
        self.cluster = cluster
        self._ns_stale: dict = {}
        self._kind_est: dict = {}
        self._kind_est_total = -1

    # --- the handler (reference: validationHandler.Handle, policy.go:139) -
    def handle(self, review_body: dict,
               cost_hint: int = 0) -> ValidationResponse:
        cost = 0.0
        tenant, lane = self._route(review_body)
        t0 = time.perf_counter()
        if self.overload is not None:
            from gatekeeper_tpu.resilience.overload import (Shed,
                                                            estimate_cost)

            try:
                cost = estimate_cost(review_body, cost_hint,
                                     self._constraint_estimate)
                # QoS kwargs only when routing produced a lane: legacy
                # gates (and test doubles) keep their admit(cost) shape
                gate = (self.overload.admit(cost, tenant=tenant,
                                            priority=lane)
                        if lane is not None
                        else self.overload.admit(cost))
                with gate:
                    resp = self._counted(review_body)
            except Shed as shed:
                resp = self._shed_response(review_body, shed)
                self._record_decision(review_body, resp, cost,
                                      shed_reason=shed.reason,
                                      tenant=tenant, lane=lane)
                self._attr_tenant(tenant, time.perf_counter() - t0, cost)
                return resp
        else:
            resp = self._counted(review_body)
        self._record_decision(review_body, resp, cost,
                              tenant=tenant, lane=lane)
        self._attr_tenant(tenant, time.perf_counter() - t0, cost)
        self._shadow_submit(review_body, resp)
        return resp

    def _shadow_submit(self, review_body: dict, resp) -> None:
        """Shadow-canary seam (replay/shadow.py): hand the admission to
        the active shadow lane, enqueue-only.  The served response is
        already final — the lane must never delay, alter, or answer for
        it, so any failure here is swallowed."""
        from gatekeeper_tpu.replay import shadow as _shadow

        lane = _shadow.active()
        if lane is None:
            return
        try:
            lane.submit(review_body, resp)
        except Exception:
            pass

    def _route(self, review_body: dict) -> tuple:
        """(tenant, PriorityLevel-or-None) for this request: the QoS
        routing when the controller carries a QoS config, else the plain
        namespace/serviceaccount tenant key — the shared attribution
        axis for the flight recorder and the cost grid (observability
        NEXT #1), present with or without QoS."""
        # duck-typed: test doubles / custom gates may not speak QoS
        route = getattr(self.overload, "route", None)
        if route is not None:
            tenant, lane = route(review_body)
            if lane is not None:
                return tenant, lane
        from gatekeeper_tpu.observability import costattr, flightrec
        from gatekeeper_tpu.resilience.qos import tenant_of_request

        if flightrec.active() is None and costattr.active() is None:
            return "", None  # nobody consumes the axis: skip the lookup
        return tenant_of_request(review_body.get("request") or {},
                                 cluster=self.cluster), None

    def _attr_tenant(self, tenant: str, seconds: float,
                     cost: float) -> None:
        """Per-tenant admission cost attribution (the ``{tenant}`` axis
        on ``gatekeeper_constraint_eval_seconds``): one wall-time sample
        per admission, charged to the request's tenant."""
        if not tenant:
            return
        from gatekeeper_tpu.observability import costattr

        attr = costattr.active()
        if attr is not None:
            attr.record_tenant(tenant, costattr.EP_WEBHOOK, seconds,
                               cost=cost)

    def _record_decision(self, review_body: dict, resp,
                         cost: float = 0.0, shed_reason: str = "",
                         tenant: str = "", lane=None) -> None:
        """Flight-recorder seam: one structured entry per decision (a
        no-op without an installed recorder)."""
        from gatekeeper_tpu.observability import flightrec

        rec = flightrec.active()
        if rec is None:
            return
        req = review_body.get("request") or {}
        if shed_reason:
            decision = "shed"
        elif resp.allowed:
            decision = "allow"
        elif resp.code == 500:
            decision = "error"
        elif resp.code == 504:
            decision = "deadline"
        else:
            decision = "deny"
        rec.record(
            "validate", decision,
            uid=resp.uid or req.get("uid", "") or "",
            obj_kind=(req.get("kind") or {}).get("kind", ""),
            name=req.get("name", "") or "",
            namespace=req.get("namespace", "") or "",
            operation=req.get("operation", "") or "",
            message=resp.message,
            cost=cost,
            reason=shed_reason,
            warnings=len(resp.warnings or []),
            code=resp.code if not resp.allowed else 0,
            overload=self.overload,
            tenant=tenant,
            cluster=self.cluster,
            priority=getattr(lane, "name", "") or "",
            # capture mode: the raw admission request rides the JSONL
            # sink line (never the ring) as the `gator replay` corpus
            request=(req if getattr(rec, "capture", False) else None),
        )

    def _counted(self, review_body: dict) -> ValidationResponse:
        if self.metrics is None:
            return self._guarded(review_body)
        from gatekeeper_tpu.metrics import registry as m

        status = "error"  # count even when _handle itself raises
        try:
            with self.metrics.timed(m.REQUEST_DURATION,
                                    self._cluster_labels()):
                resp = self._guarded(review_body)
            if not resp.allowed and resp.code == 500:
                status = "error"  # internal error surfaced as Errored deny
            else:
                status = "allow" if resp.allowed else "deny"
            return resp
        finally:
            self.metrics.inc_counter(
                m.REQUEST_COUNT,
                self._cluster_labels({"admission_status": status}))

    def _cluster_labels(self, base: Optional[dict] = None):
        """Metric labels with the fleet cluster axis when configured;
        the single-cluster shape (no cluster label) is unchanged."""
        if not self.cluster:
            return base
        out = dict(base or {})
        out["cluster"] = self.cluster
        return out

    # --- overload plumbing ------------------------------------------------
    def _constraint_estimate(self, kind: str) -> int:
        """Matched-constraint count per kind for the admission cost model
        (cost = object bytes x this).  Cached until the constraint count
        changes; an estimate, not a matcher — namespaces/labels are not
        consulted."""
        cons = self.client.constraints()
        if self._kind_est_total != len(cons):
            self._kind_est_total = len(cons)
            self._kind_est.clear()
        n = self._kind_est.get(kind)
        if n is None:
            n = 0
            for c in cons:
                entries = (c.match or {}).get("kinds") or []
                if not entries:
                    n += 1
                    continue
                for e in entries:
                    ks = e.get("kinds") or []
                    if not ks or "*" in ks or kind in ks:
                        n += 1
                        break
            n = max(1, n)
            self._kind_est[kind] = n
        return n

    def _shed_response(self, review_body: dict, shed) -> ValidationResponse:
        """Shed semantics == deadline-miss semantics: the request's
        failurePolicy decides (Ignore = allow + warning annotation,
        Fail = deny 429 with Retry-After)."""
        uid = ((review_body.get("request") or {}).get("uid", "")) or ""
        from gatekeeper_tpu.observability import tracing

        with tracing.span("webhook.shed", uid=uid, reason=shed.reason,
                          policy=self.failure_policy):
            pass
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as m

            self.metrics.inc_counter(
                m.REQUEST_COUNT,
                self._cluster_labels({"admission_status": "shed"}))
        from gatekeeper_tpu.utils.logging import log_event

        log_event("warning", "admission request shed under overload",
                  event_type="overload_shed_resolved",
                  shed_reason=shed.reason,
                  failure_policy=self.failure_policy)
        if self.fail_open:
            return ValidationResponse(
                allowed=True, uid=uid,
                warnings=[
                    f"gatekeeper shed this request under overload "
                    f"({shed.reason}); failurePolicy=Ignore admitted it "
                    f"unreviewed"],
            )
        return ValidationResponse(
            allowed=False, uid=uid, code=429,
            message=(f"gatekeeper shed this request under overload "
                     f"({shed.reason}) (failurePolicy=Fail); retry after "
                     f"{shed.retry_after_s:.0f}s"),
            retry_after_s=shed.retry_after_s or 1.0,
        )

    def _guarded(self, review_body: dict) -> ValidationResponse:
        """Deadline-budget guard (reference: the apiserver's webhook
        ``timeoutSeconds`` enforced server-side so the ANSWER — not the
        apiserver's socket timeout — honors failurePolicy).  The review
        runs on a helper thread with the budget propagated by contextvar
        (dependencies bound their own waits by it); if the budget expires
        the request resolves per failurePolicy immediately: Ignore allows
        with a warning annotation, Fail denies with reason.  A timed-out
        review thread finishes in the background and its result is
        dropped."""
        if self.deadline_budget_s <= 0:
            return self._handle(review_body)
        from gatekeeper_tpu.observability import tracing
        from gatekeeper_tpu.resilience.policy import Deadline, deadline_scope

        dl = Deadline(self.deadline_budget_s)
        done = threading.Event()
        slot: dict = {}
        parent_span = tracing.current_span()  # request span -> helper thread

        def run():
            try:
                with tracing.use_span(parent_span), deadline_scope(dl):
                    slot["resp"] = self._handle(review_body)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                slot["err"] = e
            finally:
                done.set()

        threading.Thread(target=run, daemon=True,
                         name="admit-deadline").start()
        if done.wait(dl.remaining()):
            err = slot.get("err")
            if err is not None:
                raise err
            return slot["resp"]
        uid = ((review_body.get("request") or {}).get("uid", "")) or ""
        tracing.add_event("deadline_exceeded", component="webhook",
                          policy=self.failure_policy,
                          budget_s=self.deadline_budget_s)
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as m

            self.metrics.inc_counter(
                m.RESILIENCE_DEADLINE_EXCEEDED,
                {"component": "webhook", "policy": self.failure_policy})
        from gatekeeper_tpu.utils.logging import log_event

        log_event("warning", "admission deadline budget exceeded",
                  event_type="deadline_exceeded",
                  deadline_budget_s=self.deadline_budget_s,
                  failure_policy=self.failure_policy)
        if self.fail_open:
            return ValidationResponse(
                allowed=True, uid=uid,
                warnings=[
                    f"gatekeeper review exceeded its "
                    f"{self.deadline_budget_s:.3f}s deadline budget; "
                    f"failurePolicy=Ignore admitted the request "
                    f"unreviewed"],
            )
        return ValidationResponse(
            allowed=False, uid=uid, code=504,
            message=(f"gatekeeper review exceeded its "
                     f"{self.deadline_budget_s:.3f}s deadline budget "
                     f"(failurePolicy=Fail)"),
        )

    def _handle(self, review_body: dict) -> ValidationResponse:
        req = parse_admission_review(review_body)
        username = (req.user_info or {}).get("username", "")

        # self-management bypass (policy.go:142)
        if username.startswith(GATEKEEPER_SA_PREFIX):
            return ValidationResponse(allowed=True, uid=req.uid)

        # gatekeeper resource meta-validation fast path (policy.go:359-401)
        group, _, _ = gvk_of(req.object or {})
        if group in (TEMPLATES_GROUP, CONSTRAINTS_GROUP, EXPANSION_GROUP,
                     MUTATIONS_GROUP):
            return self._validate_gatekeeper_resource(req)

        # namespace exclusion (policy.go:170)
        if self.process_excluder is not None and req.namespace:
            if self.process_excluder.is_excluded("webhook", req.namespace):
                return ValidationResponse(allowed=True, uid=req.uid)

        # review (+ expansion)
        ns_obj = self._lookup_namespace(req.namespace) if req.namespace \
            else None
        augmented = AugmentedReview(
            admission_request=req, namespace=ns_obj,
            source=SOURCE_ORIGINAL, is_admission=True,
        )
        try:
            responses = self._review(augmented)
        except Exception as e:
            # admission.Errored equivalent (policy.go:664-668): a well-formed
            # allowed=false code-500 response — an authoritative deny, like
            # the reference; the fail_open flag (--fail-open-on-error) keeps
            # the old hard-coded allow for deployments that prefer admitting
            # on webhook bugs
            if self.fail_open:
                return ValidationResponse(
                    allowed=True, uid=req.uid,
                    warnings=[f"review failed: {e}"],
                )
            return ValidationResponse(
                allowed=False, uid=req.uid, code=500,
                message=f"review failed: {e}",
            )

        expansion_warnings: list = []
        if self.expansion_system is not None and req.object:
            from gatekeeper_tpu.expansion import aggregate
            from gatekeeper_tpu.target.review import AugmentedUnstructured

            try:
                resultants = self.expansion_system.expand(
                    dict(req.object), namespace=ns_obj,
                    username=username, source=SOURCE_ORIGINAL,
                )
            except ExpansionError as e:
                # the reference errors the request, which fails open under
                # failurePolicy=ignore (policy.go:626-631) — surface a warning
                resultants = []
                expansion_warnings.append(f"expansion failed: {e}")
            for r in resultants:
                r_aug = AugmentedUnstructured(
                    object=r.obj, namespace=ns_obj, source=SOURCE_GENERATED
                )
                r_resp = self.client.review(
                    r_aug, enforcement_point=WEBHOOK_EP
                )
                aggregate.override_enforcement_action(
                    r.enforcement_action, r_resp
                )
                aggregate.aggregate_responses(r.template_name, responses,
                                              r_resp)

        denies, warns = self._partition(responses)
        warns = warns + expansion_warnings
        if self.log_denies and denies:
            from gatekeeper_tpu.utils.logging import log_deny

            for result in responses.results():
                actions = (result.scoped_enforcement_actions
                           if result.enforcement_action == "scoped"
                           else [result.enforcement_action])
                if "deny" in actions:
                    log_deny(result, req)
        if denies:
            msg = "\n".join(denies)
            resp = ValidationResponse(
                allowed=False, message=msg, code=403, warnings=warns,
                uid=req.uid,
            )
        else:
            resp = ValidationResponse(allowed=True, warnings=warns,
                                      uid=req.uid)
        if self.event_sink is not None:
            results = responses.results()
            if results:  # reference emits per result incl. dryrun-only
                self.event_sink(req, results)
        return resp

    def _lookup_namespace(self, name: str):
        """Namespace lookup with brownout degradation: at brownout level
        >= 1 — or while a breaching SLO objective holds the
        ``ns_cache_stale`` degradation action for this scope — the
        (possibly apiserver-backed) lookup is skipped and the last-seen
        value serves STALE — the first rung of the ladder, degraded
        before any request is shed."""
        from gatekeeper_tpu.resilience import overload as _ovl

        degraded = (self.overload is not None
                    and self.overload.brownout_level() >= 1) or \
            _ovl.degradation_active(_ovl.NS_CACHE_STALE, self.cluster)
        if degraded and name in self._ns_stale:
            if self.metrics is not None:
                from gatekeeper_tpu.metrics import registry as m

                self.metrics.inc_counter(
                    m.RESILIENCE_STALE_SERVED,
                    {"dependency": "webhook/namespace_lookup"})
            return self._ns_stale[name]
        ns_obj = None
        if self.snapshot is not None:
            # warm path: the watch-synced resident snapshot answers
            # without leaving the process (returns None when stale or
            # the namespace is unknown — fall through to the source)
            ns_obj = self.snapshot.namespace(name)
        if ns_obj is None:
            ns_obj = self.namespace_lookup(name)
        if self.overload is not None or \
                _ovl.active_degradations() is not None:
            if len(self._ns_stale) >= 4096 and name not in self._ns_stale:
                self._ns_stale.pop(next(iter(self._ns_stale)))
            self._ns_stale[name] = ns_obj
        return ns_obj

    def _review(self, augmented):
        req = augmented.admission_request
        from gatekeeper_tpu.observability import tracing as otel

        with otel.span("webhook.review", uid=req.uid,
                       kind=(req.kind or {}).get("kind", "")):
            return self._review_inner(augmented, req)

    def _review_inner(self, augmented, req):
        from gatekeeper_tpu.resilience.faults import fault_point

        fault_point("webhook.review", uid=req.uid,
                    kind=(req.kind or {}).get("kind", ""))
        trace = self._trace_for(req)
        if trace is None and self.batcher is not None:
            # hot path: stats ride the coalesced batch (the Batcher's own
            # stats flag); only TRACED requests bypass it — per-request
            # tracing doesn't coalesce (policy.go:632-675)
            responses = self.batcher.review(augmented)
            if self.log_stats:
                self._log_stats(responses)
            return responses
        responses = self.client.review(
            augmented, enforcement_point=WEBHOOK_EP,
            tracing=trace is not None, stats=self.log_stats,
        )
        from gatekeeper_tpu.utils.logging import log_event

        if trace is not None:
            log_event("info", "admission trace",
                      event_type="admission_trace",
                      request_user=(req.user_info or {}).get(
                          "username", ""),
                      resource_kind=(req.kind or {}).get("kind", ""),
                      trace_dump=responses.trace_dump())
            if str(trace.get("dump", "")).lower() == "all":
                log_event("info", "cache dump",
                          event_type="admission_trace_dump",
                          dump=str(self.client.dump()))
        if self.log_stats:
            self._log_stats(responses)
        return responses

    def _log_stats(self, responses) -> None:
        from gatekeeper_tpu.utils.logging import log_event

        for entry in getattr(responses, "stats_entries", []) or []:
            log_event("info", "admission stats",
                      event_type="admission_stats",
                      scope=entry.scope,
                      stats_for=entry.stats_for,
                      stats=[(s.name, s.value) for s in entry.stats])

    def _trace_for(self, req) -> Optional[dict]:
        """Config spec.validation.traces[] lookup (config_types.go:42-54:
        both user and kind must match)."""
        if self.trace_config is None:
            return None
        username = (req.user_info or {}).get("username", "")
        kind = req.kind or {}
        for t in self.trace_config() or []:
            if t.get("user", "") != username:
                continue
            want = t.get("kind") or {}
            if (want.get("group", "") == kind.get("group", "")
                    and want.get("version", "") == kind.get("version", "")
                    and want.get("kind", "") == kind.get("kind", "")):
                return t
        return None

    # --- deny/warn partition (reference: getValidationMessages,
    # policy.go:205-355) --------------------------------------------------
    @staticmethod
    def _partition(responses) -> tuple[list, list]:
        denies, warns = [], []
        for result in responses.results():
            actions = []
            if result.enforcement_action == "scoped":
                actions = result.scoped_enforcement_actions
            else:
                actions = [result.enforcement_action]
            for action in actions:
                if action == "deny":
                    denies.append(
                        f"[{_constraint_label(result)}] {result.msg}"
                    )
                elif action == "warn":
                    warns.append(
                        f"[{_constraint_label(result)}] {result.msg}"
                    )
                # dryrun: recorded in logs/metrics only
        return denies, warns

    # --- gatekeeper resource validation (policy.go:403-580) --------------
    def _validate_gatekeeper_resource(self, req) -> ValidationResponse:
        obj = req.object or {}
        group, _, kind = gvk_of(obj)
        if req.operation == "DELETE":
            return ValidationResponse(allowed=True, uid=req.uid)
        try:
            if group == TEMPLATES_GROUP and kind == "ConstraintTemplate":
                self.client.create_crd(obj)  # dry-run compile (policy.go:430)
                # also ensure the engine can compile the source
                t = ConstraintTemplate.from_unstructured(obj)
                for driver in self.client.drivers:
                    if driver.has_source_for(t):
                        break
                else:
                    raise TemplateError(
                        f"template {t.name}: no driver understands its source"
                    )
            elif group == CONSTRAINTS_GROUP:
                self.client.validate_constraint(obj)
            elif group == EXPANSION_GROUP and kind == "ExpansionTemplate":
                ExpansionTemplate.from_unstructured(obj)
            elif group == MUTATIONS_GROUP and kind in MUTATOR_KINDS:
                mutator_from_unstructured(obj)
        except (TemplateError, ConstraintError, MutatorError,
                ExpansionError, Exception) as e:
            return ValidationResponse(
                allowed=False, message=str(e), code=422, uid=req.uid
            )
        return ValidationResponse(allowed=True, uid=req.uid)


def _constraint_label(result) -> str:
    # reference formats "[<constraint metadata.name>] msg" (policy.go:346)
    c = result.constraint or {}
    return (c.get("metadata") or {}).get("name", "")


class Batcher:
    """Microbatching lane: coalesce concurrent reviews into one device pass.

    The reference bounds concurrency with a semaphore
    (--max-serving-threads, policy.go:116-120); on TPU the equivalent
    resource is the batch axis — requests wait at most ``window_s`` to share
    a verdict-grid launch (dual-queue design of SURVEY.md §7: the webhook is
    the small-batch low-latency lane, audit the big-batch lane).
    """

    def __init__(self, client, window_s: float = 0.003, max_batch: int = 64,
                 stats: bool = False, small_batch: Optional[int] = None,
                 metrics=None):
        self.client = client
        self.window_s = window_s
        self.max_batch = max_batch
        self.stats = stats
        # serving-lane contention instrumentation (VERDICT r4 weak #5):
        # how long each review sat queued before its batch ran, and the
        # coalesced batch sizes — device-lane convoying shows up here
        # while an accept-queue convoy shows up in the server's inflight
        # gauge instead
        self.metrics = metrics
        # low-latency lane: a device verdict-grid pass has ~60ms of fixed
        # per-launch cost (flatten + masks + per-template dispatch) while
        # the exact interpreter reviews one object in ~5ms — so batches
        # this size or smaller skip the grid.  The grid amortizes above
        # the crossover even on CPU (measured on one core, 42 templates:
        # interp 4.7ms/review flat; grid 63ms@1, 10ms/review@8,
        # 2.6ms/review@64), so only small batches route to the
        # interpreter.  The lanes agree bit-for-bit
        # (differential-tested); operators tune via
        # --webhook-small-batch.
        self.small_batch = 8 if small_batch is None else small_batch
        self._queue: queue.Queue = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> bool:
        """Stop AND drain: the loop keeps flushing until the queue is
        empty before exiting, so reviews queued at stop time still get
        their verdicts (the old stop dropped them — their handler threads
        waited forever on abandoned slots).  Idempotent; returns True
        when the loop exited (queue drained) within ``timeout``."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=timeout)
            return not self._thread.is_alive()
        return True

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def review(self, augmented):
        from gatekeeper_tpu.observability import tracing
        from gatekeeper_tpu.resilience.policy import (DeadlineExceeded,
                                                      current_deadline)

        done = threading.Event()
        slot: dict = {}
        # the caller's span rides the queue entry so the batch thread's
        # flush span can parent into the request's trace (cross-thread
        # propagation is explicit — contextvars don't cross the lane)
        with tracing.span("webhook.batcher.enqueue") as sp:
            self._queue.put((augmented, done, slot, time.perf_counter(),
                             tracing.current_span()))
            dl = current_deadline()
            timeout = None if dl is None else dl.remaining()
            if not done.wait(timeout):
                # the request's deadline budget expired while queued (or on
                # the device): abandon the slot — the batch loop still sets
                # it later, nobody is waiting
                sp.add_event("deadline_exceeded", component="batcher")
                raise DeadlineExceeded("batched review outlived the "
                                       "request deadline budget")
        if "error" in slot:
            raise slot["error"]
        return slot["responses"]

    def _observe_batch(self, batch) -> None:
        if self.metrics is None:
            return
        from gatekeeper_tpu.metrics import registry as m

        now = time.perf_counter()
        self.metrics.observe(m.WEBHOOK_BATCH_SIZE, len(batch))
        for entry in batch:
            self.metrics.observe(m.WEBHOOK_QUEUE_WAIT, now - entry[3])

    def _loop(self):
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                # exit only when stopped AND drained: entries queued at
                # stop time flush first (zero-loss shutdown)
                if self._stop.is_set():
                    return
                continue
            batch = [first]
            # drain whatever is already queued without blocking; the
            # window timer only runs when there IS accumulation — an idle
            # server answers a lone request immediately instead of taxing
            # every quiet-period admission the full window
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            if len(batch) > self.small_batch:
                deadline = time.monotonic() + self.window_s
                while len(batch) < self.max_batch:
                    timeout = deadline - time.monotonic()
                    if timeout <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=timeout))
                    except queue.Empty:
                        break
            reviews = [b[0] for b in batch]
            self._observe_batch(batch)
            from gatekeeper_tpu.observability import tracing

            lane = ("interp" if len(batch) <= self.small_batch
                    else "grid")
            try:
                # the flush span lives on the batch thread, parented into
                # the FIRST entry's trace (its request waited longest);
                # the other coalesced requests are recorded by count
                with tracing.span("webhook.batcher.flush",
                                  parent=batch[0][4],
                                  batch_size=len(batch), lane=lane):
                    if lane == "interp":
                        # low-latency lane: per-review exact interpreter.
                        # Each slot completes as soon as ITS review
                        # finishes (no head-of-line wait on the rest of
                        # the batch)
                        for aug, done, slot, _t, _sp in batch:
                            try:
                                slot["responses"] = self.client.review(
                                    aug, enforcement_point=WEBHOOK_EP,
                                    stats=self.stats)
                            except Exception as e:
                                slot["error"] = e
                            done.set()
                        continue
                    all_responses = self.client.review_batch(
                        reviews, enforcement_point=WEBHOOK_EP,
                        stats=self.stats,
                    )
                for (_, done, slot, _t, _sp), responses in \
                        zip(batch, all_responses):
                    # per-slot isolation: one bad request must not poison the
                    # coalesced batch (review_batch returns Exception entries)
                    if isinstance(responses, Exception):
                        slot["error"] = responses
                    else:
                        slot["responses"] = responses
                    done.set()
            except Exception as e:
                for _, done, slot, _t, _sp in batch:
                    slot["error"] = e
                    done.set()
