"""Self-signed serving certificates for the webhook.

Reference: the external ``open-policy-agent/cert-controller`` module
(go.mod:17, wired at main.go:288-315) generates a CA + serving cert, stores
them in a secret, and injects the CA bundle into webhook configurations.
Here: openssl-based generation of a CA and a SAN'd serving cert; the CA PEM
doubles as the ``caBundle`` for a ValidatingWebhookConfiguration.
"""

from __future__ import annotations

import base64
import os
import subprocess
import tempfile


class CertError(Exception):
    pass


def generate_certs(out_dir: str, service: str = "gatekeeper-webhook-service",
                   namespace: str = "gatekeeper-system",
                   days: int = 3650) -> dict:
    """Returns paths: {ca, cert, key} plus the base64 caBundle."""
    os.makedirs(out_dir, exist_ok=True)
    ca_key = os.path.join(out_dir, "ca.key")
    ca_crt = os.path.join(out_dir, "ca.crt")
    srv_key = os.path.join(out_dir, "tls.key")
    srv_csr = os.path.join(out_dir, "tls.csr")
    srv_crt = os.path.join(out_dir, "tls.crt")
    cn = f"{service}.{namespace}.svc"
    san = (f"subjectAltName=DNS:{service},DNS:{service}.{namespace},"
           f"DNS:{cn},DNS:{cn}.cluster.local,DNS:localhost,IP:127.0.0.1")

    def run(*cmd):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise CertError(f"{' '.join(cmd[:3])}...: {proc.stderr.strip()}")

    run("openssl", "genrsa", "-out", ca_key, "2048")
    run("openssl", "req", "-x509", "-new", "-nodes", "-key", ca_key,
        "-subj", "/CN=gatekeeper-ca", "-days", str(days), "-out", ca_crt)
    run("openssl", "genrsa", "-out", srv_key, "2048")
    run("openssl", "req", "-new", "-key", srv_key, "-subj", f"/CN={cn}",
        "-addext", san, "-out", srv_csr)
    with tempfile.NamedTemporaryFile("w", suffix=".cnf", delete=False) as f:
        f.write(san + "\n")
        ext = f.name
    try:
        run("openssl", "x509", "-req", "-in", srv_csr, "-CA", ca_crt,
            "-CAkey", ca_key, "-CAcreateserial", "-days", str(days),
            "-extfile", ext, "-out", srv_crt)
    finally:
        os.unlink(ext)
    with open(ca_crt, "rb") as f:
        ca_bundle = base64.b64encode(f.read()).decode()
    return {"ca": ca_crt, "cert": srv_crt, "key": srv_key,
            "ca_bundle": ca_bundle}


def webhook_configuration(ca_bundle: str, url: str) -> dict:
    """A ValidatingWebhookConfiguration pointing at this server with the CA
    injected (the cert-controller's CABundle injection equivalent)."""
    return {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "gatekeeper-validating-webhook-configuration"},
        "webhooks": [{
            "name": "validation.gatekeeper.sh",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Ignore",
            "clientConfig": {"url": url, "caBundle": ca_bundle},
            "rules": [{"apiGroups": ["*"], "apiVersions": ["*"],
                       "operations": ["CREATE", "UPDATE", "DELETE"],
                       "resources": ["*"]}],
        }],
    }


WEBHOOK_CONFIG_NAMES = (
    ("ValidatingWebhookConfiguration",
     "gatekeeper-validating-webhook-configuration"),
    ("MutatingWebhookConfiguration",
     "gatekeeper-mutating-webhook-configuration"),
)


def ensure_cluster_certs(cluster, certs_dir: str,
                         namespace: str = "gatekeeper-system",
                         secret_name: str = "gatekeeper-webhook-server-cert",
                         service: str = "gatekeeper-webhook-service",
                         webhook_configs=WEBHOOK_CONFIG_NAMES) -> tuple:
    """Cert bootstrap against a live cluster — the cert-controller
    equivalent (reference module open-policy-agent/cert-controller, wired
    main.go:288-315): consume the serving chain from the cert Secret; if
    it's empty, ONE replica generates and publishes it (last-writer-wins,
    then every replica re-reads, so all replicas converge on the stored
    chain) and injects caBundle into the webhook configurations.

    Returns (certfile, keyfile).  Files are written to ``certs_dir``,
    falling back to a scratch dir when the mount is read-only (Secret
    volumes always are — kubelet propagation isn't needed since the
    chain comes from the API)."""
    import tempfile as _tempfile

    secret_gvk = ("", "v1", "Secret")
    sec = cluster.get(secret_gvk, namespace, secret_name)
    data = (sec or {}).get("data") or {}
    if not data.get("tls.crt"):
        scratch = _tempfile.mkdtemp(prefix="gk-certgen-")
        generate_certs(scratch, service=service, namespace=namespace)

        def b64(p):
            with open(os.path.join(scratch, p), "rb") as f:
                return base64.b64encode(f.read()).decode()

        cluster.apply({
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": secret_name, "namespace": namespace,
                         "labels": {"gatekeeper.sh/system": "yes"}},
            "type": "kubernetes.io/tls",
            "data": {"tls.crt": b64("tls.crt"), "tls.key": b64("tls.key"),
                     "ca.crt": b64("ca.crt")},
        })
        # re-read: a racing replica's write wins deterministically for
        # everyone (all serve the STORED chain, one consistent CA)
        sec = cluster.get(secret_gvk, namespace, secret_name)
        data = (sec or {}).get("data") or {}
    # materialize the stored chain locally for the TLS context
    out_dir = certs_dir
    try:
        os.makedirs(out_dir, exist_ok=True)
        probe = os.path.join(out_dir, ".rw-probe")
        with open(probe, "w"):
            pass
        os.unlink(probe)
    except OSError:
        out_dir = _tempfile.mkdtemp(prefix="gk-certs-")
    for fname in ("tls.crt", "tls.key", "ca.crt"):
        blob = base64.b64decode(data.get(fname, ""))
        with open(os.path.join(out_dir, fname), "wb") as f:
            f.write(blob)
    inject_ca_bundle(cluster, data.get("ca.crt", ""), webhook_configs)
    return (os.path.join(out_dir, "tls.crt"),
            os.path.join(out_dir, "tls.key"))


def inject_ca_bundle(cluster, ca_bundle: str,
                     webhook_configs=WEBHOOK_CONFIG_NAMES) -> None:
    """Set clientConfig.caBundle on every webhook of the named
    configurations (the cert-controller's CABundle injection)."""
    if not ca_bundle:
        return
    for kind, name in webhook_configs:
        cfg = cluster.get(("admissionregistration.k8s.io", "v1", kind),
                          "", name)
        if cfg is None:
            continue
        changed = False
        for wh in cfg.get("webhooks") or []:
            cc = wh.setdefault("clientConfig", {})
            if cc.get("caBundle") != ca_bundle:
                cc["caBundle"] = ca_bundle
                changed = True
        if changed:
            cluster.apply(cfg)


def cert_expires_within(cert_path: str, seconds: float) -> bool:
    """True if the certificate at ``cert_path`` expires within ``seconds``
    (or can't be read) — drives the rotation loop."""
    import subprocess

    try:
        proc = subprocess.run(
            ["openssl", "x509", "-checkend", str(int(seconds)),
             "-noout", "-in", cert_path],
            capture_output=True, timeout=10,
        )
    except Exception:
        return True
    return proc.returncode != 0


def rotation_loop(certs_dir: str, server, stop_event,
                  check_interval_s: float = 3600.0,
                  renew_before_s: float = 90 * 24 * 3600.0,
                  cluster=None):
    """Background cert rotation (reference: open-policy-agent/cert-controller
    rotator.go wired at main.go:342): regenerate the chain when it nears
    expiry and hot-reload the serving context.  With ``cluster`` (live
    apiserver mode) the renewal republishes the Secret + caBundle so every
    replica converges on the new chain."""
    import os

    crt = os.path.join(certs_dir, "tls.crt")
    while not stop_event.wait(check_interval_s):
        if cert_expires_within(crt, renew_before_s):
            if cluster is not None:
                # wipe + re-bootstrap through the Secret (one replica
                # wins; the others pick the stored chain up on their own
                # next expiry check via ensure_cluster_certs)
                try:
                    cluster.delete({
                        "apiVersion": "v1", "kind": "Secret",
                        "metadata": {
                            "name": "gatekeeper-webhook-server-cert",
                            "namespace": "gatekeeper-system"}})
                except Exception:
                    pass
                ensure_cluster_certs(cluster, certs_dir)
            else:
                generate_certs(certs_dir)
            if server is not None:
                server.reload_certs()
