"""Self-signed serving certificates for the webhook.

Reference: the external ``open-policy-agent/cert-controller`` module
(go.mod:17, wired at main.go:288-315) generates a CA + serving cert, stores
them in a secret, and injects the CA bundle into webhook configurations.
Here: openssl-based generation of a CA and a SAN'd serving cert; the CA PEM
doubles as the ``caBundle`` for a ValidatingWebhookConfiguration.
"""

from __future__ import annotations

import base64
import os
import subprocess
import tempfile


class CertError(Exception):
    pass


def generate_certs(out_dir: str, service: str = "gatekeeper-webhook-service",
                   namespace: str = "gatekeeper-system",
                   days: int = 3650) -> dict:
    """Returns paths: {ca, cert, key} plus the base64 caBundle."""
    os.makedirs(out_dir, exist_ok=True)
    ca_key = os.path.join(out_dir, "ca.key")
    ca_crt = os.path.join(out_dir, "ca.crt")
    srv_key = os.path.join(out_dir, "tls.key")
    srv_csr = os.path.join(out_dir, "tls.csr")
    srv_crt = os.path.join(out_dir, "tls.crt")
    cn = f"{service}.{namespace}.svc"
    san = (f"subjectAltName=DNS:{service},DNS:{service}.{namespace},"
           f"DNS:{cn},DNS:{cn}.cluster.local,DNS:localhost,IP:127.0.0.1")

    def run(*cmd):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise CertError(f"{' '.join(cmd[:3])}...: {proc.stderr.strip()}")

    run("openssl", "genrsa", "-out", ca_key, "2048")
    run("openssl", "req", "-x509", "-new", "-nodes", "-key", ca_key,
        "-subj", "/CN=gatekeeper-ca", "-days", str(days), "-out", ca_crt)
    run("openssl", "genrsa", "-out", srv_key, "2048")
    run("openssl", "req", "-new", "-key", srv_key, "-subj", f"/CN={cn}",
        "-addext", san, "-out", srv_csr)
    with tempfile.NamedTemporaryFile("w", suffix=".cnf", delete=False) as f:
        f.write(san + "\n")
        ext = f.name
    try:
        run("openssl", "x509", "-req", "-in", srv_csr, "-CA", ca_crt,
            "-CAkey", ca_key, "-CAcreateserial", "-days", str(days),
            "-extfile", ext, "-out", srv_crt)
    finally:
        os.unlink(ext)
    with open(ca_crt, "rb") as f:
        ca_bundle = base64.b64encode(f.read()).decode()
    return {"ca": ca_crt, "cert": srv_crt, "key": srv_key,
            "ca_bundle": ca_bundle}


def webhook_configuration(ca_bundle: str, url: str) -> dict:
    """A ValidatingWebhookConfiguration pointing at this server with the CA
    injected (the cert-controller's CABundle injection equivalent)."""
    return {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "gatekeeper-validating-webhook-configuration"},
        "webhooks": [{
            "name": "validation.gatekeeper.sh",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Ignore",
            "clientConfig": {"url": url, "caBundle": ca_bundle},
            "rules": [{"apiGroups": ["*"], "apiVersions": ["*"],
                       "operations": ["CREATE", "UPDATE", "DELETE"],
                       "resources": ["*"]}],
        }],
    }


def cert_expires_within(cert_path: str, seconds: float) -> bool:
    """True if the certificate at ``cert_path`` expires within ``seconds``
    (or can't be read) — drives the rotation loop."""
    import subprocess

    try:
        proc = subprocess.run(
            ["openssl", "x509", "-checkend", str(int(seconds)),
             "-noout", "-in", cert_path],
            capture_output=True, timeout=10,
        )
    except Exception:
        return True
    return proc.returncode != 0


def rotation_loop(certs_dir: str, server, stop_event,
                  check_interval_s: float = 3600.0,
                  renew_before_s: float = 90 * 24 * 3600.0):
    """Background cert rotation (reference: open-policy-agent/cert-controller
    rotator.go wired at main.go:342): regenerate the chain when it nears
    expiry and hot-reload the serving context."""
    import os

    crt = os.path.join(certs_dir, "tls.crt")
    while not stop_event.wait(check_interval_s):
        if cert_expires_within(crt, renew_before_s):
            generate_certs(certs_dir)
            if server is not None:
                server.reload_certs()
