"""Admission webhook HTTP server.

Reference: controller-runtime webhook server hosting /v1/admit (policy.go),
/v1/mutate (mutation.go), /v1/admitlabel (namespacelabel.go) with TLS
(main.go:244-275, cert rotation via cert-controller).  Here: a threaded
stdlib HTTP server speaking the AdmissionReview v1 protocol; TLS is optional
(certfile/keyfile) since test harnesses terminate TLS separately.
"""

from __future__ import annotations

import base64
import json
import socket
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from gatekeeper_tpu.observability import tracing

ADMIT_PATH = "/v1/admit"
MUTATE_PATH = "/v1/mutate"
ADMIT_LABEL_PATH = "/v1/admitlabel"
HEALTH_PATH = "/healthz"
METRICS_PATH = "/metrics"
PROFILE_PATH = "/debug/profile"
TRACES_PATH = "/debug/traces"
COST_PATH = "/debug/cost"
SLO_PATH = "/debug/slo"
DECISIONS_PATH = "/debug/decisions"
OVERLOAD_PATH = "/debug/overload"
SHADOW_PATH = "/debug/shadow"


def admission_response(uid: str, allowed: bool, message: str = "",
                       code: int = 200, warnings=None, patch=None) -> dict:
    resp: dict = {"uid": uid, "allowed": allowed}
    if message or code != 200:
        resp["status"] = {"code": code if not allowed else 200,
                          "message": message}
    if warnings:
        resp["warnings"] = list(warnings)
    if patch is not None:
        resp["patchType"] = "JSONPatch"
        resp["patch"] = base64.b64encode(
            json.dumps(patch).encode()
        ).decode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": resp,
    }


class WebhookServer:
    def __init__(
        self,
        validation_handler=None,
        mutation_handler=None,
        namespace_label_handler=None,
        host: str = "127.0.0.1",
        port: int = 8443,
        certfile: Optional[str] = None,
        keyfile: Optional[str] = None,
        readiness_check=None,  # callable -> bool
        readiness_stats=None,  # callable -> dict (per-kind tracker stats)
        metrics=None,  # MetricsRegistry for /metrics exposition
        client_ca_file: Optional[str] = None,  # mTLS: require client certs
        tls_min_version: str = "1.3",  # reference --webhook-tls-min-version
        enable_profile: bool = False,  # pprof-equivalent /debug/profile
        reuse_port: bool = False,  # SO_REUSEPORT multi-worker serving
        backlog: int = 128,  # --webhook-backlog: kernel accept queue
        batcher=None,  # Batcher to drain inside stop() (zero-loss shutdown)
        mutation_batcher=None,  # MutationBatcher, drained the same way
        cost_attribution=None,  # CostAttribution for /debug/cost
        slo_engine=None,  # SLOEngine for /debug/slo
        flight_recorder=None,  # FlightRecorder for /debug/decisions
    ):
        self.validation_handler = validation_handler
        self.mutation_handler = mutation_handler
        self.namespace_label_handler = namespace_label_handler
        self.readiness_check = readiness_check
        self.readiness_stats = readiness_stats
        self.metrics = metrics
        self.enable_profile = enable_profile
        self.batcher = batcher
        self.mutation_batcher = mutation_batcher
        # the observability debug surface next to /metrics: explicit
        # instances win; None falls back to the process-global actives
        # (the install() pattern every observability piece shares)
        self._cost_attribution = cost_attribution
        self._slo_engine = slo_engine
        self._flight_recorder = flight_recorder
        # graceful drain (resilience/overload.DrainCoordinator drives the
        # process view; this event is the server-local view): once set,
        # /healthz answers 503 {"draining": true} so the LB pulls this
        # endpoint, and every reply closes its connection so kept-alive
        # clients migrate off before the listener shuts
        self._draining = threading.Event()
        # per-worker accept-lane depth (VERDICT r4 weak #5): admissions
        # currently being handled by this process + the high-water mark.
        # With --webhook-workers each SO_REUSEPORT process exports its
        # own /metrics, so imbalance across workers is directly visible.
        self._inflight = 0
        self._inflight_highwater = 0
        self._inflight_lock = threading.Lock()
        outer = self

        def _track_inflight(delta: int) -> None:
            # always counted (the drain waits on it), exported only with
            # a metrics registry
            with outer._inflight_lock:
                outer._inflight += delta
                if outer._inflight > outer._inflight_highwater:
                    outer._inflight_highwater = outer._inflight
                cur, hi = outer._inflight, outer._inflight_highwater
            if outer.metrics is None:
                return
            from gatekeeper_tpu.metrics import registry as m

            outer.metrics.set_gauge(m.WEBHOOK_INFLIGHT, cur)
            outer.metrics.set_gauge(m.WEBHOOK_INFLIGHT_HIGHWATER, hi)

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: the default 1.0 closes the connection
            # after every response, which resets concurrent clients
            # mid-reuse (every response sets Content-Length, as 1.1
            # persistence requires)
            protocol_version = "HTTP/1.1"
            # the stdlib writes a response as two send()s (header block,
            # body); with Nagle on, the body segment stalls on the
            # client's delayed ACK — a measured fixed +40ms on EVERY
            # admission reply (3.8ms handler, 48ms observed end-to-end).
            # socketserver consumes this on the handler class.
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                if self.path == HEALTH_PATH:
                    if outer._draining.is_set():
                        # drain started: 503 + explicit marker so load
                        # balancers stop routing BEFORE the listener
                        # closes (close=True also retires this kept-alive
                        # probe connection)
                        self._reply(503, {"ready": False, "draining": True},
                                    close=True)
                        return
                    ready = (outer.readiness_check is None
                             or outer.readiness_check())
                    body = {"ready": bool(ready)}
                    if outer.readiness_stats is not None:
                        # per-kind expectation stats (reference surfaces
                        # readiness progress via ready_tracker logs +
                        # the Config readiness stats, ready_tracker.go:133)
                        body["readiness"] = outer.readiness_stats()
                    self._reply(200 if ready else 503, body)
                elif self.path.startswith(PROFILE_PATH) and \
                        outer.enable_profile:
                    self._profile()
                elif self.path == TRACES_PATH:
                    # tail-sampled span ring buffer, served next to
                    # /metrics: the tracer keeps the N most recent kept
                    # traces (slow ones always kept), JSON per
                    # observability/tracing.Tracer.snapshot
                    tracer = tracing.active_tracer()
                    if tracer is None:
                        self._reply(404, {"error": "tracing not enabled "
                                                   "(run with --trace)"})
                    else:
                        self._reply(200, tracer.snapshot())
                elif self.path == COST_PATH:
                    # per-template cost attribution roll-up: "which
                    # policy is expensive" (observability/costattr.py)
                    from gatekeeper_tpu.observability import costattr

                    attr = outer._cost_attribution or costattr.active()
                    if attr is None:
                        self._reply(404, {"error": "cost attribution not "
                                                   "enabled (run with "
                                                   "--cost-attribution on)"})
                    else:
                        self._reply(200, attr.snapshot())
                elif self.path == SLO_PATH or \
                        self.path.startswith(SLO_PATH + "?"):
                    # the SLO engine's last evaluation: objectives, SLI
                    # values, multi-window burn rates, breach state,
                    # active degradations; ?cluster= filters to one
                    # cluster's fleet-scoped objectives (+ the global
                    # ones)
                    eng = outer._slo_engine
                    if eng is None:
                        self._reply(404, {"error": "SLO engine not "
                                                   "enabled (run with "
                                                   "--slo on)"})
                    else:
                        from urllib.parse import parse_qs, urlparse

                        q = parse_qs(urlparse(self.path).query)
                        cluster = (q.get("cluster") or [None])[0]
                        snap = eng.snapshot(cluster=cluster)
                        if not snap:
                            eng.tick()
                            snap = eng.snapshot(cluster=cluster)
                        self._reply(200, snap)
                elif self.path == OVERLOAD_PATH:
                    # the overload gate's lane view: limiter + brownout
                    # state, and with --qos on the per-priority /
                    # per-tenant queue, deficit, cap and heaviness state
                    # (resilience/overload.OverloadController.snapshot)
                    from gatekeeper_tpu.resilience import overload as ovl

                    ctl = ovl.active_controller()
                    if ctl is None:
                        self._reply(404, {"error": "overload limiter not "
                                                   "enabled (run with "
                                                   "--overload-limiter "
                                                   "on)"})
                    else:
                        self._reply(200, ctl.snapshot())
                elif self.path == SHADOW_PATH:
                    # the shadow canary lane: candidate-vs-serving
                    # divergence counters, recent divergent rows,
                    # promote/abort state (POST to act)
                    from gatekeeper_tpu.replay import shadow as _shadow

                    lane = _shadow.active()
                    if lane is None:
                        self._reply(404, {"error": "shadow lane not "
                                                   "enabled (run with "
                                                   "--shadow-candidate)"})
                    else:
                        self._reply(200, lane.snapshot())
                elif self.path.startswith(DECISIONS_PATH):
                    # the admission flight recorder: every decision in
                    # the ring, or one uid's history (?uid=)
                    from urllib.parse import parse_qs, urlparse

                    from gatekeeper_tpu.observability import flightrec

                    rec = outer._flight_recorder or flightrec.active()
                    if rec is None:
                        self._reply(404, {"error": "flight recorder not "
                                                   "enabled (run with "
                                                   "--flight-recorder N)"})
                    else:
                        q = parse_qs(urlparse(self.path).query)
                        uid = (q.get("uid") or [""])[0]
                        try:
                            limit = int((q.get("limit") or ["100"])[0])
                            # time-range filter: unix seconds, half-open
                            # [since, until); decision-kind filter takes
                            # ?decision=shed&decision=deny or a comma list
                            since = (q.get("since") or [None])[0]
                            until = (q.get("until") or [None])[0]
                            since = float(since) if since else None
                            until = float(until) if until else None
                        except ValueError:
                            self._reply(400, {"error": "bad limit/since/"
                                                       "until"})
                            return
                        kinds = {k for v in (q.get("decision") or [])
                                 for k in v.split(",") if k}
                        tenant = (q.get("tenant") or [None])[0]
                        cluster = (q.get("cluster") or [None])[0]
                        self._reply(200, rec.snapshot(
                            uid=uid or None, limit=limit, since=since,
                            until=until, kinds=kinds or None,
                            tenant=tenant, cluster=cluster))
                elif self.path == METRICS_PATH and outer.metrics is not None:
                    # content negotiation: OpenMetrics (exemplars on the
                    # histogram buckets + # EOF) when the scraper asks
                    # for it, the classic text format otherwise
                    accept = self.headers.get("Accept", "") or ""
                    om = "application/openmetrics-text" in accept
                    data = outer.metrics.render(openmetrics=om).encode()
                    from gatekeeper_tpu.metrics.registry import (
                        OPENMETRICS_CONTENT_TYPE, TEXT_CONTENT_TYPE)

                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        OPENMETRICS_CONTENT_TYPE if om
                        else TEXT_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self._reply(404, {"error": "not found"})

            def _profile(self):
                # pprof-equivalent: profile this process for ?seconds=N
                # (default 2) and return cProfile stats text
                import cProfile
                import io
                import pstats
                import time as _t
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                secs = min(float(q.get("seconds", ["2"])[0]), 30.0)
                prof = cProfile.Profile()
                prof.enable()
                _t.sleep(secs)
                prof.disable()
                buf = io.StringIO()
                pstats.Stats(prof, stream=buf).sort_stats(
                    "cumulative").print_stats(50)
                data = buf.getvalue().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                if self.headers.get("Content-Length") is None:
                    # keep-alive connections would desync on an undrained
                    # chunked body: require a length (411) AND close — a
                    # kept-alive socket would parse the undrained body
                    # bytes as the next request line (ADVICE r2)
                    self._reply(411, {"error": "Content-Length required"},
                                close=True)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    self._reply(400, {"error": "bad Content-Length"},
                                close=True)
                    return
                if length < 0 or length > 64 * 1024 * 1024:
                    # negative would make rfile.read block until client
                    # EOF (thread leak); cap mirrors the apiserver's
                    # admission payload bound
                    self._reply(400 if length < 0 else 413,
                                {"error": "bad Content-Length"}, close=True)
                    return
                raw = self.rfile.read(length) if length else b""
                try:
                    body = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    # the declared length may have lied: don't trust the
                    # stream position for another request
                    self._reply(400, {"error": "invalid JSON body"},
                                close=True)
                    return
                uid = ((body.get("request") or {}).get("uid", "")) or ""
                _track_inflight(+1)
                # W3C trace-context ingest: a traceparent header parents
                # the request span into the caller's trace (apiserver or
                # load generator); absent/malformed starts a fresh trace
                remote = tracing.parse_traceparent(
                    self.headers.get(tracing.TRACEPARENT_HEADER))
                try:
                    with tracing.span("webhook.request", parent=remote,
                                      path=self.path, uid=uid):
                        from gatekeeper_tpu.resilience.faults import \
                            fault_point

                        fault_point("webhook.request", path=self.path)
                        if self.path == ADMIT_PATH:
                            # the body's wire size is the cheap half of
                            # the overload cost estimate (object bytes x
                            # matched constraints)
                            self._admit(body, uid, cost_hint=length)
                        elif self.path == MUTATE_PATH:
                            self._mutate(body, uid, cost_hint=length)
                        elif self.path == ADMIT_LABEL_PATH:
                            self._admit_label(body, uid)
                        elif self.path == SHADOW_PATH:
                            self._shadow_action(body)
                        else:
                            self._reply(404, {"error": "not found"})
                except Exception as e:
                    # handler bug: admission.Errored equivalent — a
                    # well-formed allowed=false code-500 response, matching
                    # the reference (which never hard-codes allow here)
                    self._reply(200, admission_response(
                        uid, False, message=f"webhook error: {e}", code=500
                    ))
                finally:
                    _track_inflight(-1)

            def _admit(self, body, uid, cost_hint=0):
                h = outer.validation_handler
                if h is None:
                    self._reply(200, admission_response(uid, True))
                    return
                v = h.handle(body, cost_hint=cost_hint)
                headers = None
                retry_after = getattr(v, "retry_after_s", 0.0)
                if retry_after:
                    # shed under failurePolicy=Fail: the AdmissionReview
                    # carries code 429, the HTTP header tells well-behaved
                    # callers when to retry
                    headers = {"Retry-After":
                               str(max(1, int(retry_after + 0.999)))}
                self._reply(200, admission_response(
                    v.uid or uid, v.allowed, v.message, v.code, v.warnings
                ), headers=headers)

            def _mutate(self, body, uid, cost_hint=0):
                h = outer.mutation_handler
                if h is None:
                    self._reply(200, admission_response(uid, True))
                    return
                # batched handler takes the wire size as the overload
                # cost hint; the legacy per-object handler does not (a
                # TypeError probe would swallow real handler bugs, so
                # inspect once and cache on the handler)
                accepts = getattr(h, "_accepts_cost_hint", None)
                if accepts is None:
                    import inspect

                    try:
                        accepts = "cost_hint" in inspect.signature(
                            h.handle).parameters
                    except (TypeError, ValueError):
                        accepts = False
                    try:
                        h._accepts_cost_hint = accepts
                    except Exception:
                        pass
                m = (h.handle(body, cost_hint=cost_hint) if accepts
                     else h.handle(body))
                headers = None
                retry_after = getattr(m, "retry_after_s", 0.0)
                if retry_after:
                    headers = {"Retry-After":
                               str(max(1, int(retry_after + 0.999)))}
                self._reply(200, admission_response(
                    m.uid or uid, m.allowed, m.message,
                    getattr(m, "code", 200),
                    warnings=getattr(m, "warnings", None), patch=m.patch,
                ), headers=headers)

            def _admit_label(self, body, uid):
                h = outer.namespace_label_handler
                if h is None:
                    self._reply(200, admission_response(uid, True))
                    return
                r = h.handle(body)
                self._reply(200, admission_response(
                    r.uid or uid, r.allowed, r.message, r.code
                ))

            def _shadow_action(self, body):
                # POST /debug/shadow {"action": "promote"|"abort"}:
                # promote applies the candidate docs to the serving
                # client (generation-swap ride); abort stops shadowing
                from gatekeeper_tpu.replay import shadow as _shadow

                lane = _shadow.active()
                if lane is None:
                    self._reply(404, {"error": "shadow lane not enabled "
                                               "(run with "
                                               "--shadow-candidate)"})
                    return
                action = (body or {}).get("action", "")
                if action == "promote":
                    self._reply(200, lane.promote())
                elif action == "abort":
                    self._reply(200, lane.abort(
                        reason=(body or {}).get("reason", "")))
                else:
                    self._reply(400, {"error": "action must be "
                                               "promote|abort"})

            def _reply(self, status: int, payload: dict,
                       close: bool = False, headers: Optional[dict] = None):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                # traceparent emit: callers correlate their timeline with
                # the server-side request span
                tp = tracing.format_traceparent()
                if tp is not None:
                    tracing.set_attribute("http.status", status)
                    self.send_header(tracing.TRACEPARENT_HEADER, tp)
                if outer._draining.is_set():
                    # draining: retire every kept-alive connection after
                    # its in-flight response so clients reconnect through
                    # the LB (which already sees the 503 readiness)
                    close = True
                if close:
                    # send_header("Connection", "close") also sets
                    # close_connection so handle() drops the socket after
                    # this response — undrained request bodies can't
                    # desync a kept-alive connection
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)

        class _Server(ThreadingHTTPServer):
            # the socketserver default backlog of 5 resets bursts of
            # concurrent connects (the apiserver opens many at once).
            # --webhook-backlog sizes this KERNEL accept queue (unanswered
            # TCP connects); it is distinct from the limiter's cost-aware
            # admission queue (accepted requests waiting for a review
            # slot, resilience/overload.py) — see README "Overload &
            # drain semantics"
            request_queue_size = backlog

            def server_bind(self):
                if reuse_port:
                    # SO_REUSEPORT: N worker processes bind the same
                    # port and the kernel load-balances connections —
                    # the multi-process serving story for hosts with
                    # more cores than one GIL can use (the reference
                    # scales with goroutines instead, policy.go:116)
                    self.socket.setsockopt(socket.SOL_SOCKET,
                                           socket.SO_REUSEPORT, 1)
                super().server_bind()

        self._server = _Server((host, port), Handler)
        self._certfile, self._keyfile = certfile, keyfile
        self._ssl_ctx = None
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            ctx.minimum_version = {
                "1.2": ssl.TLSVersion.TLSv1_2,
                "1.3": ssl.TLSVersion.TLSv1_3,
            }.get(tls_min_version, ssl.TLSVersion.TLSv1_3)
            if client_ca_file:
                # reference --client-ca-name: verify the apiserver's client
                # certificate against this CA
                ctx.load_verify_locations(client_ca_file)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._ssl_ctx = ctx
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True
            )
        self._thread: Optional[threading.Thread] = None

    def reload_certs(self):
        """Hot-reload the certificate chain (rotation loop); new
        connections pick up the refreshed chain."""
        if self._ssl_ctx is not None and self._certfile:
            self._ssl_ctx.load_cert_chain(self._certfile, self._keyfile)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "WebhookServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    # --- graceful drain ---------------------------------------------------
    def begin_drain(self) -> None:
        """Flip into drain mode WITHOUT closing anything: /healthz answers
        503 {"draining": true} and replies close their connections, but
        the listener keeps accepting (the --shutdown-delay window where
        the LB deregisters this endpoint)."""
        self._draining.set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def stop(self, drain_timeout: float = 5.0) -> bool:
        """Zero-loss shutdown: stop accepting, then drain in-flight
        handlers AND the batcher queue within ``drain_timeout`` before
        closing.  Every admission the server accepted gets its verdict
        written back — the pre-drain ``shutdown(); server_close()``
        ordering silently dropped queued reviews.  Returns True when the
        drain completed inside the budget."""
        import time as _t

        t0 = _t.perf_counter()
        self.begin_drain()
        batchers = [b for b in (self.batcher, self.mutation_batcher)
                    if b is not None]
        with tracing.span("server.drain"):
            self._server.shutdown()  # listener stops accepting
            deadline = t0 + max(0.0, drain_timeout)
            while _t.perf_counter() < deadline:
                if self.inflight() == 0 and all(
                        b.queue_depth() == 0 for b in batchers):
                    break
                _t.sleep(0.005)
            drained = self.inflight() == 0
            for b in batchers:
                # nothing new can arrive: drain whatever is still queued
                # (abandoned deadline-missed entries included), bounded by
                # the remaining budget — the validation batcher AND the
                # mutate batcher both flush (zero-loss covers /v1/mutate)
                b.stop(timeout=max(0.1, deadline - _t.perf_counter()))
                drained = drained and b.queue_depth() == 0
            self._server.server_close()
            tracing.set_attribute("drained", drained)
            tracing.set_attribute("inflight_at_close", self.inflight())
        if self.metrics is not None:
            from gatekeeper_tpu.metrics import registry as m

            self.metrics.set_gauge(m.DRAIN_SECONDS,
                                   _t.perf_counter() - t0)
        if self._thread:
            self._thread.join(timeout=2)
        return drained
