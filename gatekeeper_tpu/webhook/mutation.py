"""Mutating admission handler (reference: pkg/webhook/mutation.go).

Only CREATE/UPDATE are mutated (mutation.go:113); the namespace comes from
a cache with API fallback (mutation.go:162-174); the response carries a
JSONPatch computed from the before/after objects (PatchResponseFromRaw,
mutation.go:214).
"""

from __future__ import annotations

import base64
import copy
import json
from dataclasses import dataclass, field
from typing import Any, Optional

from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
from gatekeeper_tpu.webhook.policy import parse_admission_review


@dataclass
class MutationResponse:
    allowed: bool = True
    patch: Optional[list] = None  # JSON-patch ops
    message: str = ""
    uid: str = ""
    code: int = 200
    warnings: list = field(default_factory=list)
    # shed under failurePolicy=Fail (batched lane): the server emits an
    # HTTP Retry-After header with this hint (0 = no header)
    retry_after_s: float = 0.0


def json_escape_pointer(seg: str) -> str:
    return seg.replace("~", "~0").replace("/", "~1")


def json_patch(before: Any, after: Any, path: str = "") -> list:
    """Minimal RFC-6902 diff between two JSON trees."""
    if type(before) is not type(after) or not isinstance(
        before, (dict, list)
    ):
        if before is after or (before == after and
                               isinstance(before, bool) ==
                               isinstance(after, bool)):
            return []
        return [{"op": "replace", "path": path or "/", "value": after}]
    if isinstance(before, dict):
        ops = []
        for k in before:
            p = f"{path}/{json_escape_pointer(str(k))}"
            if k not in after:
                ops.append({"op": "remove", "path": p})
            else:
                ops.extend(json_patch(before[k], after[k], p))
        for k in after:
            if k not in before:
                p = f"{path}/{json_escape_pointer(str(k))}"
                ops.append({"op": "add", "path": p, "value": after[k]})
        return ops
    # lists: replace wholesale on any difference (simple + correct; the
    # reference's jsondiff emits finer ops but apply-equivalence is what
    # matters)
    if before != after:
        return [{"op": "replace", "path": path or "/", "value": after}]
    return []


class MutationHandler:
    def __init__(self, mutation_system, namespace_lookup=None,
                 process_excluder=None):
        self.system = mutation_system
        self.namespace_lookup = namespace_lookup or (lambda name: None)
        self.process_excluder = process_excluder

    def handle(self, review_body: dict) -> MutationResponse:
        req = parse_admission_review(review_body)
        if req.operation not in ("CREATE", "UPDATE"):
            return MutationResponse(allowed=True, uid=req.uid)
        if req.object is None:
            return MutationResponse(allowed=True, uid=req.uid)
        if self.process_excluder is not None and req.namespace:
            if self.process_excluder.is_excluded("mutation-webhook",
                                                 req.namespace):
                return MutationResponse(allowed=True, uid=req.uid)
        ns_obj = self.namespace_lookup(req.namespace) if req.namespace else None
        before = req.object
        after = copy.deepcopy(before)
        try:
            self.system.mutate(after, namespace=ns_obj,
                               source=SOURCE_ORIGINAL)
        except Exception as e:
            return MutationResponse(allowed=True, message=str(e), uid=req.uid)
        patch = json_patch(before, after)
        return MutationResponse(allowed=True, patch=patch or None,
                                uid=req.uid)
